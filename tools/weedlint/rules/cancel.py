"""Phase-3 rules: what happens BETWEEN two awaits.

Every await is a point where the caller may be cancelled —
``CancelledError`` materializes at the suspension point and unwinds
the frame. State mutated before the await and repaired after it is
exactly the bug class this repo's review history keeps re-finding by
hand (the PR-10 FrameChannel pending-table leak, the PR-3
generation-fence cache fill, the PR-3 singleflight leader abort).
These passes ride the phase-2 symbol table + call graph so a
registration, its undo, or a re-validation may hide one resolved call
deep; the companion dynamic checker is tools/weedsched, which
actually executes the protocol cores under adversarial schedules.

* cancel-leak       — a mutation that registers state (dict/set
  insert on a ``self.`` attr, lock acquire, counter increment)
  followed by an await must pair its undo in a ``finally`` (or a
  CancelledError-catching handler), unless the registered value is a
  sanctioned detached task whose own body owns the cleanup.
* await-atomicity   — read-check → await → write over the same
  guarded ``self.`` attr with no re-read between the await and the
  write: the check is stale by the time the write lands.
* detach-discipline — a task documented to survive its caller's
  cancellation must be created via util.aio.detach, not a bare
  ``create_task`` (which drops handle retention + exception
  consumption, and hides the detachment from reviewers).
"""

from __future__ import annotations

import ast
import re

from ..callgraph import Program, iter_own_nodes
from ..core import ProgramRule
from ..symbols import FunctionInfo, chain_of
from .interproc import _short

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# mutating container calls that REGISTER an entry
_INSERT_TAILS = frozenset({"add", "append", "appendleft",
                           "setdefault"})
# calls that UNDO a registration / finish a held resource
_UNDO_TAILS = frozenset({"pop", "popleft", "discard", "remove",
                         "clear", "release"})
# container-mutating calls for the atomicity pass (supersets insert)
_MUTATE_TAILS = _INSERT_TAILS | frozenset({"update", "insert",
                                           "extend"})
# ways to spawn work whose ownership leaves this frame
_DETACH_TAILS = frozenset({"create_task", "ensure_future", "detach"})
# the one sanctioned detach helper (fixture trees mirror the layout,
# so the qual matches there too)
_SANCTIONED_DETACH_QUALS = frozenset({"seaweedfs_tpu.util.aio.detach"})

_CANCELLISH = frozenset({"BaseException", "CancelledError"})


def _self_attr(node: ast.AST) -> str | None:
    """'X' when `node` is exactly the attribute `self.X`."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _self_chain(node: ast.AST) -> tuple[str, ...] | None:
    chain = chain_of(node)
    if chain and chain[0] == "self" and len(chain) >= 2:
        return chain
    return None


def _walk_stmts(stmts):
    """Every node under `stmts`, never entering nested defs/lambdas."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _Events:
    """Direct registration/undo events of one function body."""

    __slots__ = ("regs", "undos")

    def __init__(self):
        # attr -> [(lineno, kind, value_expr|None)]
        self.regs: dict[str, list] = {}
        # attr -> [lineno]
        self.undos: dict[str, list] = {}


def _direct_events(fi: FunctionInfo) -> _Events:
    ev = _Events()
    for node in iter_own_nodes(fi.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        ev.regs.setdefault(attr, []).append(
                            (node.lineno, "insert", node.value))
        elif isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target)
            if attr:
                if isinstance(node.op, ast.Add):
                    ev.regs.setdefault(attr, []).append(
                        (node.lineno, "increment", None))
                elif isinstance(node.op, ast.Sub):
                    ev.undos.setdefault(attr, []).append(node.lineno)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        ev.undos.setdefault(attr, []).append(
                            node.lineno)
        elif isinstance(node, ast.Call):
            chain = _self_chain(node.func)
            if not chain or len(chain) != 3:
                continue
            attr, tail = chain[1], chain[2]
            if tail in _INSERT_TAILS:
                ev.regs.setdefault(attr, []).append(
                    (node.lineno, "insert", None))
            elif tail == "acquire":
                ev.regs.setdefault(attr, []).append(
                    (node.lineno, "acquire", None))
            elif tail in _UNDO_TAILS:
                ev.undos.setdefault(attr, []).append(node.lineno)
    return ev


def _is_detach_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = chain_of(node.func)
    return bool(chain) and chain[-1] in _DETACH_TAILS


class CancelLeakRule(ProgramRule):
    id = "cancel-leak"
    title = "state registered before an await, undo not finally'd"
    rationale = ("every await is a cancellation point: "
                 "CancelledError materializes there and unwinds the "
                 "frame, skipping any sequential or except-handler "
                 "cleanup. A pending-table insert, lock acquire or "
                 "in-flight counter increment whose undo is not in a "
                 "finally (or a CancelledError-catching handler) "
                 "leaks the entry the first time a caller is "
                 "cancelled mid-await — the PR-10 FrameChannel "
                 "pending-registration leak. The registration or its "
                 "undo may hide one resolved call deep; handing the "
                 "registered value to a sanctioned detached task "
                 "moves the cleanup obligation into that task.")
    example = ("self._pending[req_id] = fut\n"
               "await writer.drain()          # cancelled here ->\n"
               "self._pending.pop(req_id)     # never runs: entry "
               "leaks")
    fix = ("wrap the awaits in try/finally with the undo in the "
           "finally (pop/discard/release/decrement are idempotent "
           "spellings), or detach the owning work via "
           "util.aio.detach")

    def run(self, program: Program, reporter) -> None:
        self._summaries: dict[str, _Events] = {}
        for fi in program.table.functions.values():
            if fi.is_async:
                self._check(program, fi, reporter)

    def _summary(self, fi: FunctionInfo) -> _Events:
        ev = self._summaries.get(fi.qual)
        if ev is None:
            ev = self._summaries[fi.qual] = _direct_events(fi)
        return ev

    def _check(self, program: Program, fi: FunctionInfo,
               reporter) -> None:
        awaits = [n for n in iter_own_nodes(fi.node)
                  if isinstance(n, ast.Await)]
        if not awaits:
            return
        ev = _direct_events(fi)
        sites = {s.node: s for s in program.calls.get(fi.qual, ())}
        # registration/undo one resolved self-call deep (sync callees
        # only: an async callee has its own cancellation points and is
        # analyzed as its own frame)
        for site in sites.values():
            if site.kind != "resolved" or site.target is None \
                    or site.target.is_async \
                    or not site.chain or site.chain[0] != "self":
                continue
            sub = self._summary(site.target)
            for attr, regs in sub.regs.items():
                kinds = {k for _, k, _ in regs}
                for kind in sorted(kinds):
                    ev.regs.setdefault(attr, []).append(
                        (site.lineno, kind, None))
            for attr in sub.undos:
                ev.undos.setdefault(attr, []).append(site.lineno)

        parent = _parent_map(fi.node)
        detached_names = {
            t.id for n in iter_own_nodes(fi.node)
            if isinstance(n, ast.Assign) and _is_detach_call(n.value)
            for t in n.targets if isinstance(t, ast.Name)}

        for attr in sorted(set(ev.regs) & set(ev.undos)):
            undo_max = max(ev.undos[attr])
            for lineno, kind, value in sorted(ev.regs[attr]):
                if kind == "insert" and value is not None and (
                        _is_detach_call(value)
                        or (isinstance(value, ast.Name)
                            and value.id in detached_names)):
                    continue        # ownership moved to a detached task
                window = [a for a in awaits
                          if lineno < a.lineno < undo_max]
                bad = next(
                    (a for a in window
                     if not self._covered(program, fi, a, attr,
                                          parent, sites)), None)
                if bad is None:
                    continue
                what = {"insert": f"entry registered in self.{attr}",
                        "acquire": f"self.{attr} acquired",
                        "increment": f"self.{attr} incremented",
                        }[kind]
                reporter.report(
                    self, fi.rel, lineno,
                    f"{what} in {fi.name}() but the await at line "
                    f"{bad.lineno} is not covered by a finally that "
                    f"undoes it — a caller cancelled at that await "
                    f"leaks the registration; move the undo into a "
                    f"try/finally around the awaits")
                break               # one finding per (function, attr)

    def _covered(self, program: Program, fi: FunctionInfo,
                 await_node: ast.AST, attr: str, parent: dict,
                 sites: dict) -> bool:
        """Is `await_node` inside a try whose finally (or a
        CancelledError-catching handler) undoes `attr`, directly or
        one resolved call deep?"""
        cur = await_node
        while True:
            anc = parent.get(id(cur))
            if anc is None or isinstance(anc, _FUNC_NODES):
                return False
            if isinstance(anc, ast.Try) and not self._in_cleanup(
                    anc, cur):
                if anc.finalbody and self._undoes(
                        program, anc.finalbody, attr, sites):
                    return True
                for h in anc.handlers:
                    if self._handler_cancellish(h) and self._undoes(
                            program, h.body, attr, sites):
                        return True
            cur = anc

    @staticmethod
    def _in_cleanup(try_node: ast.Try, child: ast.AST) -> bool:
        """Is `child` the try's handler/finally arm (rather than under
        its body/orelse)? Cleanup code cancelled mid-cleanup is out of
        scope for this pass."""
        if isinstance(child, ast.ExceptHandler):
            return True
        return any(child is stmt for stmt in try_node.finalbody)

    @staticmethod
    def _handler_cancellish(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True                         # bare except
        names = [handler.type] if not isinstance(
            handler.type, ast.Tuple) else list(handler.type.elts)
        for n in names:
            chain = chain_of(n)
            if chain and chain[-1] in _CANCELLISH:
                return True
        return False

    def _undoes(self, program: Program, stmts, attr: str,
                sites: dict) -> bool:
        for node in _walk_stmts(stmts):
            if isinstance(node, ast.Call):
                chain = _self_chain(node.func)
                if chain and len(chain) == 3 and chain[1] == attr \
                        and chain[2] in _UNDO_TAILS:
                    return True
                site = sites.get(node)
                if site is not None and site.kind == "resolved" \
                        and site.target is not None \
                        and site.chain and site.chain[0] == "self" \
                        and attr in self._summary(site.target).undos:
                    return True
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Sub) \
                    and _self_attr(node.target) == attr:
                return True
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and _self_attr(t.value) == attr:
                        return True
        return False


def _parent_map(fn_node: ast.AST) -> dict:
    cache: dict[int, ast.AST] = {}
    stack = [fn_node]
    while stack:
        cur = stack.pop()
        for child in ast.iter_child_nodes(cur):
            cache[id(child)] = cur
            stack.append(child)
    return cache


class AwaitAtomicityRule(ProgramRule):
    id = "await-atomicity"
    title = "guarded check is stale by the time the write lands"
    rationale = ("`if <reads self.X>: ... await ...; <writes "
                 "self.X>` — the await is a scheduling point where "
                 "any other task may mutate self.X, so the check the "
                 "branch was entered on no longer holds when the "
                 "write executes: the PR-3 generation-fence bug "
                 "shape, where a cache fill raced a delete across an "
                 "await and re-pinned stale bytes. The write must "
                 "re-validate after the await — re-read the guard, "
                 "compare a generation token, or go through a "
                 "fenced helper (set_if) that re-checks inside; the "
                 "re-validation may hide one resolved call deep.")
    example = ("if fid not in self._cache:\n"
               "    data = await fetch(fid)    # delete() races here\n"
               "    self._cache[fid] = data    # stale bytes pinned")
    fix = ("re-check the guard (or a generation token captured "
           "before the await) after the await, or route the write "
           "through a compare-and-set helper that re-validates")

    def run(self, program: Program, reporter) -> None:
        self._read_memo: dict[str, set] = {}
        for fi in program.table.functions.values():
            if fi.is_async:
                self._check(program, fi, reporter)

    def _callee_reads(self, target: FunctionInfo) -> set:
        reads = self._read_memo.get(target.qual)
        if reads is None:
            reads = set()
            for node in iter_own_nodes(target.node):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load):
                    attr = _self_attr(node)
                    if attr:
                        reads.add(attr)
            self._read_memo[target.qual] = reads
        return reads

    def _check(self, program: Program, fi: FunctionInfo,
               reporter) -> None:
        sites = {s.node: s for s in program.calls.get(fi.qual, ())}
        for node in iter_own_nodes(fi.node):
            if isinstance(node, ast.If):
                guard = {c[1] for n in ast.walk(node.test)
                         if isinstance(n, ast.Attribute)
                         and (c := _self_chain(n))}
                if guard:
                    self._check_branch(program, fi, node, guard,
                                       sites, reporter)

    def _check_branch(self, program: Program, fi: FunctionInfo,
                      if_node: ast.If, guard: set, sites: dict,
                      reporter) -> None:
        body = list(if_node.body)
        awaits: list[ast.Await] = []
        writes: list = []       # (node, attr, via_call)
        for node in _walk_stmts(body):
            if isinstance(node, ast.Await):
                awaits.append(node)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr in guard:
                            writes.append((node, attr, None))
            elif isinstance(node, ast.Call):
                chain = _self_chain(node.func)
                if chain and len(chain) == 3 and chain[1] in guard \
                        and chain[2] in _MUTATE_TAILS:
                    writes.append((node, chain[1], None))
                site = sites.get(node)
                if site is not None and site.kind == "resolved" \
                        and site.target is not None \
                        and not site.target.is_async \
                        and site.chain and site.chain[0] == "self":
                    sub = _direct_events(site.target)
                    for attr in set(sub.regs) & guard:
                        writes.append((node, attr, site.target))
        if not awaits or not writes:
            return
        for wnode, attr, via in writes:
            if via is not None and attr in self._callee_reads(via):
                continue        # fenced helper re-checks inside
            wsub = {id(n) for n in ast.walk(wnode)}
            prior = [a for a in awaits
                     if a.lineno <= wnode.lineno
                     and id(a) not in wsub]
            # the collapsed form `self.X[k] = await f()` awaits inside
            # the write statement itself: the check is equally stale
            prior += [a for a in awaits if id(a) in wsub
                      and isinstance(wnode, ast.Assign)]
            if not prior:
                continue
            last_await = max(a.lineno for a in prior)
            if self._revalidated(program, fi, body, attr, last_await,
                                 wnode, sites):
                continue
            reporter.report(
                self, fi.rel, wnode.lineno,
                f"self.{attr} is checked before the await at line "
                f"{last_await} and written here without "
                f"re-validation — the guard is stale by write time "
                f"(another task may have mutated self.{attr} during "
                f"the await); re-check the guard or use a fenced "
                f"compare-and-set after the await")
            return              # one finding per guarded branch

    def _revalidated(self, program: Program, fi: FunctionInfo,
                     body, attr: str, after_line: int,
                     wnode: ast.AST, sites: dict) -> bool:
        wsub = {id(n) for n in ast.walk(wnode)}
        for node in _walk_stmts(body):
            lineno = getattr(node, "lineno", None)
            if lineno is None or id(node) in wsub \
                    or lineno <= after_line or lineno > wnode.lineno:
                continue
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and _self_attr(node) == attr:
                return True
            if isinstance(node, ast.Call):
                site = sites.get(node)
                if site is not None and site.kind == "resolved" \
                        and site.target is not None \
                        and site.chain and site.chain[0] == "self" \
                        and attr in self._callee_reads(site.target):
                    return True
        return False


_DETACH_DOC_RE = re.compile(
    r"(?i)\bdetach(ed|es|ing)?\b|\bsurviv\w*\b|\boutliv\w*\b"
    r"|fire[-_ ]?and[-_ ]?forget")


class DetachDisciplineRule(ProgramRule):
    id = "detach-discipline"
    title = "documented-detached task spawned with bare create_task"
    rationale = ("a task that must survive its caller's cancellation "
                 "carries obligations a bare create_task drops: the "
                 "handle must be retained (unreferenced tasks may be "
                 "GC'd mid-flight), its terminal exception consumed "
                 "(or asyncio logs 'never retrieved' at exit), and "
                 "the detachment made visible to reviewers. "
                 "util.aio.detach is the one sanctioned spelling; a "
                 "create_task whose adjacent comment promises "
                 "detach/survive/outlive semantics re-implements it "
                 "ad hoc — the PR-3 singleflight leader did exactly "
                 "this. Loop tasks whose handle the owner retains "
                 "and cancels on shutdown are NOT detached and stay "
                 "plain create_task.")
    example = ("# runs DETACHED: caller cancellation must not stop it\n"
               "task = asyncio.create_task(self._run(key, fn))")
    fix = "task = aio.detach(self._run(key, fn))"

    def run(self, program: Program, reporter) -> None:
        line_cache: dict[str, list[str]] = {}
        for fi in program.table.functions.values():
            if fi.qual in _SANCTIONED_DETACH_QUALS:
                continue
            lines = line_cache.get(fi.module.name)
            if lines is None:
                lines = fi.module.src.splitlines()
                line_cache[fi.module.name] = lines
            for node in iter_own_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                chain = chain_of(node.func)
                if not chain or chain[-1] not in ("create_task",
                                                  "ensure_future"):
                    continue
                doc = self._adjacent_comments(lines, node)
                if doc and _DETACH_DOC_RE.search(doc):
                    reporter.report(
                        self, fi.rel, node.lineno,
                        f"task documented to outlive its caller "
                        f"({_short(fi.qual)}()) is spawned with bare "
                        f"{chain[-1]} — use util.aio.detach, the "
                        f"sanctioned detach helper (retains the "
                        f"handle, consumes the terminal exception, "
                        f"and names the intent)")

    @staticmethod
    def _adjacent_comments(lines: list[str], node: ast.Call) -> str:
        """The contiguous comment block directly above the call plus
        inline comments on the call's own lines."""
        out: list[str] = []
        i = node.lineno - 2                     # line above, 0-based
        while i >= 0 and lines[i].lstrip().startswith("#"):
            out.append(lines[i].lstrip())
            i -= 1
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        for ln in range(node.lineno - 1, min(end, len(lines))):
            _, _, comment = lines[ln].partition("#")
            if comment:
                out.append(comment)
        return "\n".join(out)
