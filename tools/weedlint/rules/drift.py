"""docs-drift: the five hand-maintained catalogs must match the code.

Every PR since the flag/metric/event/failpoint tables were written has
edited the code side without a machine check on the doc side. This
pass diffs artifacts.py's AST extraction against the markdown catalogs
in both directions:

- **undocumented** — a flag/metric/event type/failpoint site/debug
  route that exists in the code but appears nowhere in the scanned
  catalogs (README.md, OBSERVABILITY.md, ROBUSTNESS.md, EC.md);
  anchored at the defining code line.
- **dead** — a catalog entry naming nothing in the code (the flag was
  renamed, the site unplanted, the metric dropped); anchored at the
  doc line, so the finding lands where the fix goes.

Doc anchors can't carry suppression comments — drift is always fixed
in-tree, never excused.
"""

from __future__ import annotations

from .. import artifacts
from ..core import ProgramRule


class DocsDriftRule(ProgramRule):
    id = "docs-drift"
    title = "code and catalog docs disagree on a name"
    rationale = ("the flag, metric, journal-event, failpoint and "
                 "/debug-route tables in README/OBSERVABILITY/"
                 "ROBUSTNESS/EC are the operator's interface to the "
                 "cluster, and they are four PRs deep in hand edits "
                 "with no machine check — a site the chaos runbook "
                 "names but nobody plants, or a flag the code grew "
                 "that no doc admits, both rot silently. This pass "
                 "extracts each family from the AST and diffs both "
                 "directions against the catalogs.")
    example = ("ROBUSTNESS.md: | `replication.s3` | ... |   # no "
               "failpoints.fail('replication.s3') anywhere in the tree")
    fix = ("undocumented: add the name to its catalog table; dead: "
           "delete the row (or re-plant the code it promised)")
    report_everywhere = True

    # (family, mention-check, claim-check) wiring
    def run(self, program, reporter) -> None:
        # only meaningful over a tree that carries the package CLI —
        # diffing the repo's catalogs against a fixture snippet (or an
        # empty table) would report every claim as dead
        if not any(m.rel.endswith("seaweedfs_tpu/cli.py")
                   for m in program.table.modules.values()):
            return
        code = artifacts.extract_code(program.table)
        # module attributes, not defaults: tests point REPO/DOC_FILES
        # at fixture catalogs
        docs = artifacts.extract_docs(artifacts.REPO,
                                      artifacts.DOC_FILES)
        catalogs = "/".join(artifacts.DOC_FILES)

        def undocumented(family: str, items, documented) -> None:
            for name, art in sorted(items.items()):
                if not documented(name):
                    reporter.report(
                        self, art.rel, art.line,
                        f"{family} {name!r} exists in code but none "
                        f"of {catalogs} documents it — add it to the "
                        f"catalog table")

        def dead(family: str, claims, live) -> None:
            seen = set()
            for c in claims:
                if c.name in seen or live(c.name):
                    continue
                seen.add(c.name)
                reporter.report(
                    self, c.rel, c.line,
                    f"{family} {c.name!r} is documented here but the "
                    f"code defines no such name — delete the entry or "
                    f"restore the code it promises")

        undocumented("flag", code.flags,
                     lambda n: n in docs.flag_mentions)
        undocumented("metric", code.metrics,
                     lambda n: artifacts.metric_documented(
                         n, docs.metric_mentions))
        undocumented("event type", code.events,
                     lambda n: n in docs.event_mentions)
        undocumented("failpoint site", code.failpoints,
                     lambda n: n in docs.failpoint_mentions)
        undocumented("debug route", code.routes,
                     lambda n: n in docs.route_mentions)

        dead("flag", docs.flag_claims,
             lambda n: n in code.flags)
        dead("metric", [c for c in docs.metric_claims],
             lambda n: artifacts.metric_claim_live(n, code.metrics))
        dead("journal event type", docs.event_claims,
             lambda n: n in code.events)
        dead("failpoint site", docs.failpoint_claims,
             lambda n: n in code.failpoints)
        dead("debug route", docs.route_claims,
             lambda n: n in code.routes)
