"""Rule: silent broad exception handlers (the original pass 1)."""

from __future__ import annotations

import ast

from ..core import FileContext, Rule

BROAD = {"Exception", "BaseException"}


def is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True                          # bare except:
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def is_silent(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue))
               for s in handler.body)


class SilentExceptRule(Rule):
    id = "silent-except"
    title = "silent broad exception handler"
    rationale = ("`except Exception: pass` turns real faults "
                 "invisible — a wedged peer, a torn write and a typo "
                 "all vanish identically. Narrow handlers may still "
                 "swallow (idempotent deletes, probe loops); broad "
                 "ones must log.")
    example = "try: g()\nexcept Exception:\n    pass"
    fix = ("narrow the exception type, or glog the fault before "
           "swallowing it")
    node_types = (ast.ExceptHandler,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.ExceptHandler)
        if is_broad(node) and is_silent(node):
            what = "bare except" if node.type is None \
                else "except Exception"
            ctx.report(self, node,
                       f"silent {what}: pass — narrow the exception "
                       f"type and/or glog the fault")
