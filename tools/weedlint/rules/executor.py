"""Rule: contextvar/trace propagation into executor thunks.

asyncio does NOT copy contextvars into run_in_executor threads, so a
store/EC span started in a worker thread parents under nothing and
the trace breaks exactly at the layer whose latency matters most —
the PR-4 class fixed by util/tracing.run_in_executor. Every direct
loop.run_in_executor call must either go through that helper or
visibly copy the context itself.
"""

from __future__ import annotations

import ast

from ..core import FileContext, Rule


def _subtree_mentions(node: ast.AST, names: set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in names:
            return True
    return False


class ExecutorCtxRule(Rule):
    id = "executor-ctx"
    title = "run_in_executor without context propagation"
    rationale = ("contextvars (tracing parenthood, request ids) do "
                 "not cross into executor threads on their own; a raw "
                 "loop.run_in_executor severs the trace at the "
                 "disk/EC layer. util/tracing.run_in_executor pays "
                 "the context copy only while a trace is active.")
    example = ("await loop.run_in_executor(None,\n"
               "    lambda: store.read_needle(vid, nid))")
    fix = ("await tracing.run_in_executor(fn, *args), or wrap the "
           "thunk in contextvars.copy_context().run yourself")
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr == "run_in_executor"):
            return
        # the blessed helper itself: tracing.run_in_executor(fn, ...)
        if isinstance(f.value, ast.Name) and f.value.id == "tracing":
            return
        if ctx.rel.endswith("util/tracing.py"):
            return                  # the helper's own implementation
        # visible propagation: copy_context at the call site itself...
        if _subtree_mentions(node, {"copy_context"}):
            return
        # ...or a contextvars.copy_context() call in the enclosing
        # function whose result the thunk runs under. A bare name
        # `ctx`/`run` is NOT evidence — an argument that happens to be
        # called ctx must not disable the rule.
        fn = ctx.enclosing_function(node)
        if fn is not None and any(
                isinstance(s, ast.Call) and _subtree_mentions(
                    s.func, {"copy_context"})
                for s in ast.walk(fn)):
            return
        ctx.report(self, node,
                   "raw run_in_executor severs contextvars (trace "
                   "parenthood) at the thread boundary — use "
                   "tracing.run_in_executor(fn, *args) or copy the "
                   "context explicitly")
