"""Phase-2 rules: properties that only hold (or break) across
function and file boundaries.

Each pass propagates one per-function fact over the call graph:

* transitive-blocking — "does this sync function (or anything it
  calls inline) hit a blocking primitive?" propagated up to every
  async caller that isn't separated from it by an executor boundary.
* lock-order          — per-function "locks acquired (transitively)"
  sets; acquiring B while holding A adds edge A→B; a cycle in the
  merged edge graph is a potential deadlock.
* timeout-discipline  — every outbound aiohttp/socket/pool call must
  carry an explicit timeout, traced through wrapper helpers that
  forward a `timeout=None` parameter.
* transitive-orphan-span — a span started here and finished in a
  callee must provably finish on some path of that callee (or the
  ownership must visibly move elsewhere).
* unresolved-call     — the advisory precision diagnostic: every call
  the bounded resolver gave up on, so the callgraph's blind spots are
  measurable (and ceilinged by tests/test_callgraph.py).
"""

from __future__ import annotations

import ast
import re

from ..callgraph import Program, iter_own_nodes
from ..core import ProgramRule
from ..symbols import FunctionInfo, chain_of
from .asynchrony import LOCKISH_RE
from .cache import _HTTP_VERBS

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _short(qual: str) -> str:
    """seaweedfs_tpu.storage.store.Store.write -> store.Store.write"""
    parts = qual.split(".")
    return ".".join(parts[-3:]) if len(parts) > 3 else qual


class TransitiveBlockingRule(ProgramRule):
    id = "transitive-blocking"
    title = "blocking I/O reachable from async def through sync calls"
    rationale = ("phase 1's blocking-io rule sees one file: a sync "
                 "helper that does os.pread three calls below an "
                 "`async def` stalls the event loop exactly as hard, "
                 "but no single-file walk can see the chain. This "
                 "pass propagates 'reaches a blocking primitive' over "
                 "the call graph and reports at the async caller's "
                 "call site; executor boundaries "
                 "(tracing.run_in_executor / loop.run_in_executor / "
                 "to_thread) terminate the walk — thunks run off the "
                 "loop.")
    example = ("async def h(req):\n"
               "    return self._load(req.vid)   # sync\n"
               "def _load(self, vid):\n"
               "    return _read_meta(vid)       # sync\n"
               "def _read_meta(vid):\n"
               "    return open(path(vid)).read()  # 3 deep: stalls "
               "the loop")
    fix = ("route the outermost sync call through "
           "tracing.run_in_executor, or make the chain async down to "
           "the primitive")

    def run(self, program: Program, reporter) -> None:
        for fi in program.table.functions.values():
            if not fi.is_async:
                continue
            for site in program.calls.get(fi.qual, ()):
                if site.kind != "resolved" or site.target is None \
                        or site.target.is_async \
                        or site.target.is_generator:
                    continue
                path = program.blocking_path(site.target)
                if path is None:
                    continue
                what = path[-1][2]
                chain = " -> ".join(_short(q) for q, _, _ in path)
                reporter.report(
                    self, fi.rel, site.lineno,
                    f"async {fi.name}() reaches blocking {what}() on "
                    f"the event loop via {chain} — the whole chain "
                    f"runs inline; route it through "
                    f"tracing.run_in_executor")


def _lock_identity(fi: FunctionInfo, expr: ast.AST) -> str | None:
    """Stable cross-file identity for an acquired lock, or None when
    the receiver can't be pinned (bare parameters alias anything —
    guessing would fabricate deadlocks)."""
    chain = chain_of(expr)
    if not chain or not LOCKISH_RE.search(chain[-1]):
        return None
    if chain[0] == "self" and fi.cls is not None:
        if len(chain) == 2:
            return f"{fi.cls.qual}.{chain[1]}"
        if len(chain) == 3:
            tq = fi.cls.attr_types.get(chain[1])
            if tq:
                return f"{tq}.{chain[2]}"
        return None
    if len(chain) == 1 and chain[0] in fi.module.lock_names:
        return f"{fi.module.name}.{chain[0]}"
    if len(chain) == 2:
        mod = fi.module
        target = None
        fs = mod.from_symbols.get(chain[0])
        if fs:
            target = f"{fs[0]}.{fs[1]}" if fs[0] else fs[1]
        elif chain[0] in mod.imports:
            target = mod.imports[chain[0]]
        if target:
            return f"{target}.{chain[1]}"
        if chain[0] in fi.var_types:
            return f"{fi.var_types[chain[0]]}.{chain[1]}"
    return None


class LockOrderRule(ProgramRule):
    id = "lock-order"
    title = "lock-order inversion across the call graph"
    rationale = ("two code paths that acquire the same two locks in "
                 "opposite orders deadlock the first time they "
                 "interleave — and the two halves of the inversion "
                 "are usually in different modules, invisible to any "
                 "per-file pass. Each function's (transitive) lock "
                 "acquisition set is propagated over the call graph; "
                 "acquiring B anywhere under a held A adds edge A→B, "
                 "and a cycle in the merged graph is a potential "
                 "deadlock. Locks are identified by their owning "
                 "class/module attribute; bare lock parameters are "
                 "skipped (aliases would fabricate cycles).")
    example = ("# store.py               # vacuum.py\n"
               "with self._vol_lock:     with store._map_lock:\n"
               "    self._map_lock...        store._vol_lock...")
    fix = ("pick one global order for the two locks and acquire in "
           "that order on every path (or collapse the critical "
           "sections)")

    def run(self, program: Program, reporter) -> None:
        self._program = program
        self._closure_memo: dict[str, set[str]] = {}
        self._closure_cut = False
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        for fi in program.table.functions.values():
            for node in iter_own_nodes(fi.node):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                for item in node.items:
                    held = _lock_identity(fi, item.context_expr)
                    if held is None:
                        continue
                    self._edges_under(fi, held, node, edges)
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        cyclic = _cyclic_nodes(adj)
        for (a, b), (rel, line, via) in sorted(edges.items()):
            if a in cyclic and b in cyclic and _reaches(adj, b, a):
                via_txt = f" (via {via})" if via else ""
                reporter.report(
                    self, rel, line,
                    f"lock-order inversion: acquires {_short(b)} "
                    f"while holding {_short(a)}{via_txt}, and another "
                    f"path acquires them in the opposite order — "
                    f"potential deadlock; pick one global order")

    def _edges_under(self, fi: FunctionInfo, held: str,
                     with_node, edges) -> None:
        """Locks acquired anywhere inside `with_node`'s body — nested
        `with`s directly, call sites through their transitive
        acquisition closure."""
        program = self._program
        sites = {s.node: s for s in program.calls.get(fi.qual, ())}
        stack = list(with_node.body)
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_NODES):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    inner = _lock_identity(fi, item.context_expr)
                    if inner and inner != held:
                        edges.setdefault(
                            (held, inner), (fi.rel, node.lineno, ""))
            if isinstance(node, ast.Call) and node in sites:
                site = sites[node]
                if site.kind == "resolved" and site.target is not None:
                    for inner in self._closure(site.target):
                        if inner != held:
                            edges.setdefault(
                                (held, inner),
                                (fi.rel, site.lineno,
                                 _short(site.target.qual)))
            stack.extend(ast.iter_child_nodes(node))

    def _closure(self, fi: FunctionInfo,
                 _stack: set | None = None) -> set[str]:
        """Every lock identity `fi` may acquire, transitively."""
        memo = self._closure_memo
        if fi.qual in memo:
            return memo[fi.qual]
        stack = _stack if _stack is not None else set()
        if fi.qual in stack:
            self._closure_cut = True
            return set()
        stack.add(fi.qual)
        outer_cut = self._closure_cut
        self._closure_cut = False
        out: set[str] = set()
        for node in iter_own_nodes(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ident = _lock_identity(fi, item.context_expr)
                    if ident:
                        out.add(ident)
        for site in self._program.calls.get(fi.qual, ()):
            if site.kind == "resolved" and site.target is not None:
                out |= self._closure(site.target, stack)
        stack.discard(fi.qual)
        # A set computed after a callee walk was cut at an in-stack
        # node is only a lower bound for THIS query's stack —
        # memoizing it would permanently drop a cycle member's lock
        # edges for every later caller.
        if not self._closure_cut:
            memo[fi.qual] = out
        self._closure_cut = self._closure_cut or outer_cut
        return out


def _cyclic_nodes(adj: dict[str, set[str]]) -> set[str]:
    """Nodes on some directed cycle (Tarjan SCCs of size > 1;
    self-edges are excluded upstream by construction)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    out: set[str] = set()
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    out.update(scc)

    for v in list(adj):
        if v not in index:
            strongconnect(v)
    return out


def _reaches(adj: dict[str, set[str]], src: str, dst: str) -> bool:
    seen = {src}
    stack = [src]
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        for nxt in adj.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


_SESSIONISH = re.compile(r"(?i)(sess|session|http|client|pool|chan"
                         r"|channel)$")
_TIMEOUT_NAME = re.compile(r"(?i)(timeout|deadline)")
TIMEOUT_SCOPE = ("seaweedfs_tpu/",)


def _has_timeout_words(fn_node: ast.AST) -> bool:
    for node in iter_own_nodes(fn_node):
        name = ""
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.keyword):
            name = node.arg or ""
        if name and _TIMEOUT_NAME.search(name):
            return True
    return False


def _params_with_defaults(fn_node) -> dict[str, "ast.AST | None"]:
    """param name -> default node (None = required)."""
    args = fn_node.args
    out: dict[str, ast.AST | None] = {}
    pos = args.posonlyargs + args.args
    defaults = [None] * (len(pos) - len(args.defaults)) \
        + list(args.defaults)
    for a, d in zip(pos, defaults):
        out[a.arg] = d
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        out[a.arg] = d
    return out


class TimeoutDisciplineRule(ProgramRule):
    id = "timeout-discipline"
    title = "outbound call without an explicit timeout"
    rationale = ("an outbound HTTP/socket call with no timeout turns "
                 "one wedged peer into a wedged caller — the PR-2 "
                 "class where a single stalled upload held its slot "
                 "for the old 120s session total. The site must carry "
                 "`timeout=`, or its function/receiver must visibly "
                 "own one (a ClientTimeout/…_timeout reference, or a "
                 "pool whose constructor defaults it); a wrapper that "
                 "merely forwards `timeout=None` passes the "
                 "obligation to every caller, and this pass follows "
                 "it there through the call graph.")
    example = ("async def probe(self, url):\n"
               "    async with self._http.get(url) as r:  # no "
               "timeout anywhere in reach\n"
               "        return r.status")
    fix = ("pass timeout=aiohttp.ClientTimeout(...) (or the helper's "
           "timeout parameter) at the call site")

    def run(self, program: Program, reporter) -> None:
        table = program.table
        # pass 1: leaf sites + discover forwarding wrappers
        wrappers: dict[str, str] = {}     # fi.qual -> timeout param
        for fi in table.functions.values():
            if not any(s in fi.rel for s in TIMEOUT_SCOPE):
                continue
            params = _params_with_defaults(fi.node)
            fn_has_words = None           # computed lazily
            for node in iter_own_nodes(fi.node):
                if not (isinstance(node, ast.Call)
                        and self._outbound(node)):
                    continue
                kw = next((k for k in node.keywords
                           if k.arg == "timeout"), None)
                if kw is not None:
                    if isinstance(kw.value, ast.Constant) \
                            and kw.value.value is None:
                        reporter.report(
                            self, fi.rel, node.lineno,
                            f"outbound {self._label(node)} call with "
                            f"explicit timeout=None — a wedged peer "
                            f"wedges this caller forever")
                    elif isinstance(kw.value, ast.Name) \
                            and kw.value.id in params:
                        d = params[kw.value.id]
                        if d is None or (isinstance(d, ast.Constant)
                                         and d.value is None):
                            # required params force callers to choose;
                            # a None default forwards the obligation
                            if d is not None:
                                wrappers[fi.qual] = kw.value.id
                    continue
                if fn_has_words is None:
                    fn_has_words = _has_timeout_words(fi.node)
                if fn_has_words or self._receiver_owns_timeout(
                        program, fi, node):
                    continue
                reporter.report(
                    self, fi.rel, node.lineno,
                    f"outbound {self._label(node)} call with no "
                    f"timeout in reach (no timeout= kwarg, no "
                    f"timeout/deadline reference in "
                    f"{fi.name}(), none owned by the receiver) — a "
                    f"wedged peer wedges this caller forever")
        # pass 2: callers of forwarding wrappers must supply one
        for fi in table.functions.values():
            if not any(s in fi.rel for s in TIMEOUT_SCOPE):
                continue
            fn_has_words = None
            for site in program.calls.get(fi.qual, ()):
                if site.kind != "resolved" or site.target is None \
                        or site.target.qual not in wrappers:
                    continue
                param = wrappers[site.target.qual]
                kw = next((k for k in site.node.keywords
                           if k.arg == param), None)
                if kw is not None and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is None):
                    continue
                if fn_has_words is None:
                    fn_has_words = _has_timeout_words(fi.node)
                if fn_has_words:
                    continue
                reporter.report(
                    self, fi.rel, site.lineno,
                    f"call to {_short(site.target.qual)}() leaves its "
                    f"{param}=None default — the wrapper forwards the "
                    f"timeout obligation to this caller; pass "
                    f"{param}=")

    @staticmethod
    def _outbound(node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _HTTP_VERBS:
            chain = chain_of(f.value)
            if chain and _SESSIONISH.search(chain[-1]):
                return True
        chain = chain_of(f)
        if chain in (("socket", "create_connection"),):
            return True
        return bool(chain) and chain[-1] == "urlopen"

    @staticmethod
    def _label(node: ast.Call) -> str:
        chain = chain_of(node.func)
        return ".".join(chain[-2:]) if chain else "<dynamic>"

    @staticmethod
    def _attr_constructed_with_timeout(program: Program, owner_qual,
                                       attr: str) -> bool:
        """Was `self.<attr>` (following one @property hop) ever
        assigned a call carrying `timeout=<non-None>` anywhere in
        `owner_qual`'s MRO? That is receiver ownership: a session
        built `tls.make_session(timeout=ClientTimeout(...))` bounds
        every request it ever issues."""
        owner = program.table.class_by_qual(owner_qual) \
            if isinstance(owner_qual, str) else owner_qual
        if owner is None:
            return False
        for ci in program.table.iter_mro(owner):
            name = ci.prop_aliases.get(attr, attr)
            if name in ci.timeout_attrs:
                return True
        return False

    def _receiver_owns_timeout(self, program: Program,
                               fi: FunctionInfo,
                               node: ast.Call) -> bool:
        """`self._http.get(...)` is fine when `_http` was constructed
        with a session-level timeout (`tls.make_session(timeout=
        ClientTimeout(total=60))`), and `self._pool.request(...)` when
        the pool's own constructor defaults a timeout
        (connpool.SyncHttpPool's shape). One @property hop
        (`env.http` -> `_session`) is followed."""
        f = node.func
        if not isinstance(f, ast.Attribute):
            return False
        chain = chain_of(f.value)
        if not chain:
            return False
        ci = None
        if chain[0] == "self" and fi.cls is not None and len(chain) == 2:
            if self._attr_constructed_with_timeout(program, fi.cls,
                                                   chain[1]):
                return True
            tq = fi.cls.attr_types.get(chain[1])
            ci = program.table.class_by_qual(tq) if tq else None
        elif len(chain) == 1 and chain[0] in fi.var_types:
            ci = program.table.class_by_qual(fi.var_types[chain[0]])
        elif len(chain) == 2 and chain[0] in fi.var_types:
            # env.http.get(...): typed local/param, attribute receiver
            return self._attr_constructed_with_timeout(
                program, fi.var_types[chain[0]], chain[1])
        if ci is None:
            return False
        init = program.table.lookup_method(ci, "__init__")
        if init is None:
            return False
        for name, default in _params_with_defaults(init.node).items():
            if _TIMEOUT_NAME.search(name) and default is not None \
                    and not (isinstance(default, ast.Constant)
                             and default.value is None):
                return True
        return False


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class TransitiveOrphanSpanRule(ProgramRule):
    id = "transitive-orphan-span"
    title = "span started here can leak through a callee"
    rationale = ("a span that never finishes squats in the in-flight "
                 "table forever and skews /debug/requests; phase 1's "
                 "span-finish rule checks the finally discipline of "
                 "an explicit finish, but a span handed to ANOTHER "
                 "function must provably finish there — and 'the "
                 "callee finishes it' is invisible to a per-file "
                 "walk. This pass follows the handle: started and "
                 "dropped, or transferred to a resolved callee that "
                 "never finishes (nor re-transfers) it on any path, "
                 "is a leak at the start site.")
    example = ("sp = tracing.start('volume', 'read')\n"
               "self._serve(req, sp)   # _serve never calls "
               "sp.finish()")
    fix = ("use `with tracing.start(...)`, or make the receiving "
           "function finish the span in a finally")

    def run(self, program: Program, reporter) -> None:
        self._program = program
        self._parent_maps: dict[str, dict] = {}
        for fi in program.table.functions.values():
            for node in iter_own_nodes(fi.node):
                if isinstance(node, ast.Call) \
                        and self._is_span_start(node):
                    self._check_start(fi, node, reporter)

    @staticmethod
    def _is_span_start(node: ast.Call) -> bool:
        chain = chain_of(node.func)
        return bool(chain) and len(chain) >= 2 \
            and chain[-2] == "tracing" \
            and chain[-1] in ("start", "start_root")

    def _check_start(self, fi: FunctionInfo, start: ast.Call,
                     reporter) -> None:
        parent = self._parent_of(fi, start)
        if isinstance(parent, ast.withitem):
            return                              # with tracing.start()
        if isinstance(parent, (ast.Return, ast.Call, ast.Yield,
                               ast.YieldFrom)):
            return                              # ownership moves out
        if not (isinstance(parent, ast.Assign)
                and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            if isinstance(parent, ast.Expr):
                reporter.report(
                    self, fi.rel, start.lineno,
                    f"span started and immediately dropped in "
                    f"{fi.name}() — it can never finish and squats "
                    f"in the in-flight table forever")
            return
        name = parent.targets[0].id
        verdict = self._span_escapes(fi, name, start, set())
        if verdict is True:
            return
        if verdict is False:
            reporter.report(
                self, fi.rel, start.lineno,
                f"span {name!r} started in {fi.name}() never "
                f"finishes on any path (no finish(), no `with`, no "
                f"ownership transfer) — it leaks into the in-flight "
                f"table")
        else:                    # (callee_qual, reason)
            callee = verdict[0]
            reporter.report(
                self, fi.rel, start.lineno,
                f"span {name!r} started in {fi.name}() is handed to "
                f"{_short(callee)}(), which never finishes it on any "
                f"path — the span leaks transitively")

    def _parent_of(self, fi: FunctionInfo, node: ast.AST):
        # per-function parent map, built lazily and cached on the rule
        # instance (FunctionInfo has __slots__ — it can't carry it)
        cache = self._parent_maps.get(fi.qual)
        if cache is None:
            cache = {}
            stack = [fi.node]
            while stack:
                cur = stack.pop()
                for child in ast.iter_child_nodes(cur):
                    cache[id(child)] = cur
                    stack.append(child)
            self._parent_maps[fi.qual] = cache
        return cache.get(id(node))

    def _span_escapes(self, fi: FunctionInfo, name: str,
                      start: ast.AST, visited: set):
        """True = finished/owned somewhere; False = provably dropped;
        (callee_qual,) = transferred to a resolved callee that never
        finishes it."""
        program = self._program
        sites = {s.node: s for s in program.calls.get(fi.qual, ())}
        transferred_dead = None
        for node in iter_own_nodes(fi.node):
            if isinstance(node, ast.withitem) \
                    and isinstance(node.context_expr, ast.Name) \
                    and node.context_expr.id == name:
                return True
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == name \
                        and f.attr in ("finish", "end", "close"):
                    return True
                if node is not start:
                    for idx, a in enumerate(node.args):
                        if name not in _names_in(a):
                            continue
                        handled = self._callee_finishes(
                            sites.get(node), idx, visited)
                        if handled is True:
                            return True
                        if handled is None:
                            return True      # unresolved: assume owned
                        site = sites.get(node)
                        transferred_dead = (
                            site.target.qual if site and site.target
                            else "<callee>",)
                    for k in node.keywords:
                        if name in _names_in(k.value):
                            return True      # kwarg mapping: assume ok
            if isinstance(node, ast.Assign) and node.value is not None \
                    and not (isinstance(node.value, ast.Call)
                             and node.value is start) \
                    and name in _names_in(node.value):
                return True                  # aliased / stored away
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and node.value is not None \
                    and name in _names_in(node.value):
                return True
        return transferred_dead if transferred_dead else False

    def _callee_finishes(self, site, arg_idx: int, visited: set):
        """Does the resolved callee finish (or take ownership of) its
        parameter at `arg_idx`? None = can't tell (unresolved callee
        or unmappable parameter) — treated as owned, bounded
        optimism."""
        if site is None or site.kind != "resolved" \
                or site.target is None:
            return None
        target = site.target
        if target.qual in visited:
            return True                      # cycle: give up quietly
        visited.add(target.qual)
        args = target.node.args
        pos = args.posonlyargs + args.args
        offset = 1 if target.cls is not None \
            and not isinstance(site.node.func, ast.Name) else 0
        idx = arg_idx + offset
        if idx >= len(pos):
            return None
        pname = pos[idx].arg
        program = self._program
        sites = {s.node: s for s in program.calls.get(target.qual, ())}
        for node in iter_own_nodes(target.node):
            if isinstance(node, ast.withitem) \
                    and isinstance(node.context_expr, ast.Name) \
                    and node.context_expr.id == pname:
                return True
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == pname \
                        and f.attr in ("finish", "end", "close"):
                    return True
                for i2, a in enumerate(node.args):
                    if pname in _names_in(a):
                        sub = self._callee_finishes(
                            sites.get(node), i2, visited)
                        if sub is not False:
                            return True
                for k in node.keywords:
                    if pname in _names_in(k.value):
                        return True
            if isinstance(node, ast.Assign) and node.value is not None \
                    and pname in _names_in(node.value):
                return True
            if isinstance(node, (ast.Return, ast.Yield,
                                 ast.YieldFrom)) \
                    and node.value is not None \
                    and pname in _names_in(node.value):
                return True
        return False


class UnresolvedCallRule(ProgramRule):
    id = "unresolved-call"
    title = "call the bounded resolver could not pin (advisory)"
    rationale = ("the whole-program passes are only as good as call "
                 "resolution, and resolution is deliberately bounded "
                 "(no type inference, no dataflow). This diagnostic "
                 "makes the blind spots visible: every call that is "
                 "neither resolved in-tree nor provably external. It "
                 "never gates — tests/test_callgraph.py ceilings the "
                 "rate so precision can't silently rot.")
    example = "self._volume(vid).write(n)   # receiver is a call result"
    fix = ("nothing to fix at the site; if the rate creeps up, teach "
           "symbols.py the new idiom")
    advisory = True

    def __init__(self, emit_sites: bool = False):
        self.emit_sites = emit_sites

    def run(self, program: Program, reporter) -> None:
        if not self.emit_sites:
            return
        for fi in program.table.functions.values():
            for site in program.calls.get(fi.qual, ()):
                if site.kind == "unresolved":
                    reporter.report(
                        self, fi.rel, site.lineno,
                        f"unresolved call {site.what}() in "
                        f"{fi.name}() — invisible to the "
                        f"whole-program passes")
