"""Rules: metrics + tracing-span hygiene (the original passes 2-3)."""

from __future__ import annotations

import ast
import re

from ..core import FileContext, Rule

METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary"}
# SeaweedFS_ prefix then a lowercase-led snake-ish name; interior
# camelCase segments are allowed (the reference's own idiom:
# SeaweedFS_volumeServer_request_total)
METRIC_NAME_RE = re.compile(r"^SeaweedFS_[a-z][A-Za-z0-9_]*$")
SPAN_NAME_RE = re.compile(r"^(sp|rsp|span|.*_span|.*_sp)$")


def _ctor_name(node: ast.Call) -> str:
    func = node.func
    return func.id if isinstance(func, ast.Name) else (
        func.attr if isinstance(func, ast.Attribute) else "")


class MetricNameRule(Rule):
    id = "metric-name"
    title = "metric name outside the SeaweedFS_ namespace"
    rationale = ("every Counter/Gauge/Histogram shares one registry "
                 "and one /metrics page; names must carry the "
                 "SeaweedFS_ prefix with a lowercase-led tail so the "
                 "whole-host merge and dashboards can rely on one "
                 "namespace.")
    example = 'Counter("my_requests_total", "requests")'
    fix = 'rename to SeaweedFS_<subsystem>_<what>_total'
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        if _ctor_name(node) not in METRIC_CTORS or len(node.args) < 1:
            return
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value,
                                                          str):
            if not METRIC_NAME_RE.match(first.value):
                ctx.report(self, node,
                           f"metric name {first.value!r} must match "
                           f"SeaweedFS_[a-z]... (one registry "
                           f"namespace, lowercase-led)")


class MetricHelpRule(Rule):
    id = "metric-help"
    title = "metric registered without help text"
    rationale = ("the help string is the only documentation a metric "
                 "gets on /metrics; an empty one ships an unlabeled "
                 "number to every dashboard.")
    example = 'Histogram("SeaweedFS_request_seconds", "")'
    fix = "write one line of help text"
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        name = _ctor_name(node)
        if name not in METRIC_CTORS or len(node.args) < 1:
            return
        help_arg = node.args[1] if len(node.args) > 1 else None
        if help_arg is None or (isinstance(help_arg, ast.Constant)
                                and not str(help_arg.value or "").strip()):
            ctx.report(self, node,
                       f"metric {name} needs non-empty help text")


class SpanFinishRule(Rule):
    id = "span-finish"
    title = "span.finish() outside a finally block"
    rationale = ("an exception on any path between start() and "
                 "finish() leaks an unfinished span out of the "
                 "in-flight table; `with tracing.start(...)` or a "
                 "finally makes every path finish.")
    example = 'sp = tracing.start("x", "y")\nsp.finish("ok")'
    fix = "use `with tracing.start(...)` or move finish() into finally"
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "finish"
                and isinstance(func.value, ast.Name)
                and SPAN_NAME_RE.match(func.value.id)):
            return
        if ctx.in_finally(node):
            return
        ctx.report(self, node,
                   f"span {func.value.id}.finish() outside a finally "
                   f"— an exception path would leak the span (use "
                   f"`with` or move the finish into finally)")
