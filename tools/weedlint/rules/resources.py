"""Rule: resources constructed outside with / try-finally close.

An aiohttp.ClientSession, socket or file handle bound to a local and
closed only on the happy path leaks on the first exception — fd
exhaustion under fault injection is exactly how the chaos soak finds
these. Ownership transfers (returned, stored on self, passed to
another call, yielded) are exempt: the receiver owns the close.
"""

from __future__ import annotations

import ast

from ..core import FileContext, Rule
from .asynchrony import tail_name

_CTOR_ATTRS: dict[str, set[str]] = {
    "aiohttp": {"ClientSession", "TCPConnector", "UnixConnector"},
    "socket": {"socket"},
    "os": {"fdopen"},
    "io": {"open"},
    "mmap": {"mmap"},
    "tempfile": {"NamedTemporaryFile", "TemporaryFile",
                 "TemporaryDirectory"},
}
_CTOR_NAMES = {"open", "ClientSession"}
_CLOSERS = {"close", "aclose", "shutdown", "terminate", "stop",
            "release_conn", "unlink", "cleanup"}


def _ctor_label(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name) and f.id in _CTOR_NAMES:
        return f.id
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.attr in _CTOR_ATTRS.get(f.value.id, ())):
        return f"{f.value.id}.{f.attr}"
    return ""


class ResourceWithRule(Rule):
    id = "resource-with"
    title = "resource constructed outside with/try-finally"
    rationale = ("a session/socket/file closed only on the happy path "
                 "leaks its fd (and for ClientSession, its connector "
                 "pool) on the first exception; under fault injection "
                 "that compounds into fd exhaustion. `with` / close in "
                 "a finally makes every path release.")
    example = ("sess = aiohttp.ClientSession()\n"
               "await sess.get(url)    # an exception leaks the pool\n"
               "await sess.close()")
    fix = ("`async with aiohttp.ClientSession() as sess:` (or close "
           "in a finally); for long-lived members, store on self and "
           "close in the owner's close()")
    node_types = (ast.Call,)

    def visit(self, ctx: FileContext, node: ast.AST) -> None:
        assert isinstance(node, ast.Call)
        label = _ctor_label(node)
        if not label:
            return
        parent = ctx.parent(node)
        # unwrap an `await aiohttp.ClientSession()`-style wrapper
        if isinstance(parent, ast.Await):
            parent = ctx.parent(parent)
        if isinstance(parent, ast.withitem):
            return                              # with CTOR() as x: ...
        if isinstance(parent, ast.Attribute):
            ctx.report(self, node,
                       f"{label}(...).{parent.attr} chains off an "
                       f"unbound resource — nothing can ever close "
                       f"it; bind it in a `with`")
            return
        if isinstance(parent, ast.Expr):
            ctx.report(self, node,
                       f"{label}() result discarded — the resource "
                       f"can never be closed")
            return
        if isinstance(parent, ast.Assign) \
                and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            self._check_scope(ctx, node, label,
                              parent.targets[0].id, parent)
        # every other shape (return CTOR(), f(CTOR()), self.x = CTOR(),
        # containers, ann-assign to attributes) transfers ownership —
        # the receiver is responsible, often a different file.

    def _check_scope(self, ctx: FileContext, node: ast.Call,
                     label: str, name: str, assign: ast.Assign) -> None:
        scope = ctx.enclosing_function(node) or ctx.tree
        body = scope.body if not isinstance(scope, ast.Lambda) else []
        closed_in_finally = False
        for sub in ast.walk(ast.Module(body=list(body),
                                       type_ignores=[])):
            # ownership escapes: someone else closes it
            if isinstance(sub, ast.withitem) \
                    and tail_name(sub.context_expr) == name:
                return
            if isinstance(sub, ast.Return) and sub.value is not None \
                    and name in _names_in(sub.value):
                return
            if isinstance(sub, (ast.Yield, ast.YieldFrom)) \
                    and sub.value is not None \
                    and name in _names_in(sub.value):
                return
            if isinstance(sub, ast.Call) and sub is not node:
                f = sub.func
                if isinstance(f, ast.Attribute) \
                        and tail_name(f.value) == name \
                        and f.attr in _CLOSERS:
                    if ctx.in_finally(sub):
                        closed_in_finally = True
                    continue
                for a in list(sub.args) + [k.value for k in
                                           sub.keywords]:
                    if name in _names_in(a):
                        return              # handed to another owner
            if isinstance(sub, ast.Assign) and sub is not assign \
                    and sub.value is not None \
                    and name in _names_in(sub.value):
                return                      # aliased / stored away
        if not closed_in_finally:
            ctx.report(self, node,
                       f"{label}() bound to {name!r} with no `with` "
                       f"and no close() in a finally — an exception "
                       f"path leaks the resource")


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}
