"""Per-line suppression comments.

Grammar (same line as the finding, or alone on the line above):

    # weedlint: ignore[rule-id] reason text
    # weedlint: ignore[rule-a,rule-b] one reason for both

The reason is mandatory: a suppression is a reviewed claim that the
finding is a false positive (or deliberately accepted), and the claim
must be written down. A reasonless or malformed suppression is itself
a finding (``suppress-format``), and — when the full ruleset runs — a
suppression that matches no finding is flagged too
(``unused-suppression``) so dead suppressions can't accrete the way
stale ``noqa``s do.
"""

from __future__ import annotations

import io
import re
import tokenize

SUPPRESS_RE = re.compile(
    r"#\s*weedlint:\s*ignore\[([^\]]*)\]\s*(.*)$")
# anything that *tries* to be a weedlint comment but doesn't parse
ATTEMPT_RE = re.compile(r"#\s*weedlint\b")
RULE_ID_RE = re.compile(r"^[a-z][a-z0-9-]*$")


class Suppression:
    __slots__ = ("line", "rules", "reason", "used")

    def __init__(self, line: int, rules: set[str], reason: str):
        self.line = line            # line the suppression covers
        self.rules = rules
        self.reason = reason
        self.used = False


def _comments(src: str) -> list[tuple[int, str, bool]]:
    """(line, comment_text, own_line) for every real COMMENT token —
    tokenize, not a regex over lines, so the suppression grammar
    quoted in a docstring (like this module's) is never parsed."""
    out = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                own_line = tok.line[:tok.start[1]].strip() == ""
                out.append((tok.start[0], tok.string, own_line))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass                        # unparseable tail: no suppressions
    return out


def parse(ctx) -> list[Suppression]:
    """Scan comment tokens for suppressions. A comment-only line
    covers the next line; a trailing comment covers its own line.
    Malformed attempts are reported via ctx (suppress-format)."""
    sups: list[Suppression] = []
    for i, raw, own_line in _comments(ctx.src):
        if "weedlint" not in raw:
            continue
        m = SUPPRESS_RE.search(raw)
        if not m:
            if ATTEMPT_RE.search(raw):
                ctx.report("suppress-format", i,
                           "malformed weedlint comment — want "
                           "`# weedlint: ignore[rule-id] reason`")
            continue
        ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
        reason = m.group(2).strip()
        bad = [r for r in ids if not RULE_ID_RE.match(r)]
        if not ids or bad:
            ctx.report("suppress-format", i,
                       f"bad rule id(s) {sorted(bad) or '[]'} in "
                       f"suppression — ids are kebab-case, see "
                       f"--list-rules")
            continue
        if not reason:
            ctx.report("suppress-format", i,
                       f"suppression for {sorted(ids)} has no reason — "
                       f"every ignore must say why")
            continue
        covered = i + 1 if own_line else i
        sups.append(Suppression(covered, ids, reason))
    return sups


def mark(findings, sups) -> None:
    """Mark findings matched by a suppression (shared by the per-file
    walk and the phase-2 merge — whole-program findings ride the same
    per-line comments)."""
    if not sups:
        return
    by_line: dict[int, list[Suppression]] = {}
    for s in sups:
        by_line.setdefault(s.line, []).append(s)
    for f in findings:
        if f.rule in ("suppress-format", "unused-suppression"):
            continue                # the meta-rules are unsuppressable
        for s in by_line.get(f.line, ()):
            if f.rule in s.rules:
                f.suppressed = True
                f.suppress_reason = s.reason
                s.used = True
                break


def unused_findings(path, rel, sups) -> list:
    """Findings for suppressions nothing matched. Only meaningful
    after EVERY phase that could use them has run — the driver calls
    this last."""
    from .core import Finding
    return [Finding(
        path=path, rel=rel, line=s.line, rule="unused-suppression",
        message=f"suppression for {sorted(s.rules)} matches no "
                f"finding — delete it (the bug it excused is gone)")
        for s in sups if not s.used]


def apply(ctx, *, check_unused: bool = True) -> list:
    """Parse + apply suppressions for one file's phase-1 findings;
    returns the suppressions so later phases can match against them.

    ``check_unused`` is off when only a rule subset runs (--select) —
    a suppression for an unselected rule would look unused even though
    the full run needs it — and off in the two-phase driver, which
    judges unused-ness only after phase 2."""
    sups = parse(ctx)
    if not sups:
        return []
    mark(ctx.findings, sups)
    if check_unused:
        for s in sups:
            if not s.used:
                ctx.report("unused-suppression", s.line,
                           f"suppression for {sorted(s.rules)} matches "
                           f"no finding — delete it (the bug it excused "
                           f"is gone)")
    ctx.findings.sort(key=lambda f: (f.line, f.rule))
    return sups
