"""Phase-2 symbol table: every module, class, method and function in
the scanned tree, indexed for call resolution.

Phase 1 is a per-file walk and can never see past a file boundary;
the whole-program passes (callgraph.py) need to answer "what does
`self.client.upload` resolve to" from another module entirely. This
module builds the shared substrate once per run:

- modules keyed by dotted name (``seaweedfs_tpu.util.client``),
  derived from the path relative to the scan roots' parent;
- per-module import maps (``import a.b as x`` / ``from a import b``,
  including relative forms) so attribute chains resolve across files;
- classes with their methods, base-class chains (bounded MRO walk) and
  an *attribute-type* map harvested from ``self.x = ClassName(...)``
  assignments — the heuristic that lets ``self.client.upload(...)``
  resolve to ``WeedClient.upload``;
- per-function local variable types from ``x = ClassName(...)``
  assignments, same idea one scope down.

Resolution is explicitly bounded: anything this table cannot prove is
reported (not guessed) by callgraph.py as an ``unresolved-call`` so
precision stays measurable — see STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast
import os
import sys

from .core import iter_py_files, relpath

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# modules that are never in-tree: calls into them are "external", not
# "unresolved" (the unresolved-call rate must measure OUR resolution
# power, not the size of the stdlib)
EXTERNAL_MODULES = set(getattr(sys, "stdlib_module_names", ())) | {
    "aiohttp", "jax", "jaxlib", "numpy", "np", "prometheus_client",
    "pytest", "requests", "PIL", "yaml", "multidict", "yarl",
    "sqlite3", "uvloop", "fuse",
}


class FunctionInfo:
    """One def/async def: module-level function or class method."""

    __slots__ = ("module", "cls", "name", "qual", "node", "is_async",
                 "is_generator", "rel", "lineno", "var_types",
                 "var_funcs")

    def __init__(self, module: "ModuleInfo", cls: "ClassInfo | None",
                 node: ast.AST):
        self.module = module
        self.cls = cls
        self.name = node.name
        self.qual = (f"{module.name}.{cls.name}.{node.name}" if cls
                     else f"{module.name}.{node.name}")
        self.node = node
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        # calling a generator function executes NOTHING — its body
        # runs at next()/iteration time (which this tree drives from
        # the executor: h_volume_tail's locked per-record reads), so
        # blocking propagation must not flow through the call edge
        self.is_generator = _has_own_yield(node)
        self.rel = module.rel
        self.lineno = node.lineno
        self.var_types: dict[str, str] = {}   # local name -> chain str
        # local name -> FunctionInfo, from bound-method aliases
        # (`f = self.method`) and `functools.partial(self.method, x)`
        # — callgraph.py fills this and resolves `f()` through it
        self.var_funcs: dict[str, "FunctionInfo"] = {}

    def __repr__(self) -> str:  # pragma: no cover
        return f"<fn {self.qual}>"


class ClassInfo:
    __slots__ = ("module", "name", "qual", "node", "bases", "methods",
                 "attr_types", "prop_aliases", "timeout_attrs")

    def __init__(self, module: "ModuleInfo", node: ast.ClassDef):
        self.module = module
        self.name = node.name
        self.qual = f"{module.name}.{node.name}"
        self.node = node
        self.bases = [_chain_str(b) for b in node.bases]
        self.bases = [b for b in self.bases if b]
        self.methods: dict[str, FunctionInfo] = {}
        self.attr_types: dict[str, str] = {}  # self.x -> ctor chain str
        # @property def http(self): return self._session  ->
        # {'http': '_session'}: lets receiver checks follow the one
        # hop of indirection the accessor idiom adds
        self.prop_aliases: dict[str, str] = {}
        # attrs ever assigned `<call>(..., timeout=<non-None>)` —
        # evidence the object was constructed owning a deadline
        # (sessions built by tls.make_session(timeout=...))
        self.timeout_attrs: set[str] = set()


class ModuleInfo:
    __slots__ = ("name", "rel", "path", "tree", "src", "imports",
                 "from_symbols", "functions", "classes", "lock_names")

    def __init__(self, name: str, rel: str, path: str,
                 tree: ast.AST, src: str):
        self.name = name
        self.rel = rel
        self.path = path
        self.tree = tree
        self.src = src
        self.imports: dict[str, str] = {}       # alias -> dotted module
        self.from_symbols: dict[str, tuple[str, str]] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        # module-level names bound to Lock()/RLock()/Semaphore()
        self.lock_names: set[str] = set()

    @property
    def package(self) -> str:
        if self.rel.endswith("__init__.py"):
            return self.name
        return self.name.rpartition(".")[0]


def _has_own_yield(fn_node: ast.AST) -> bool:
    """Yield/YieldFrom in `fn_node`'s OWN body (nested defs are their
    own schedulable units)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(node, (*_FUNC_NODES, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _chain_str(node: ast.AST) -> str:
    parts = chain_of(node)
    return ".".join(parts) if parts else ""


def chain_of(node: ast.AST) -> tuple[str, ...] | None:
    """Flatten `a.b.c` / `self.x.f` into ('a','b','c'). A chain rooted
    at a call (``get_loop().sendfile``) keeps a '<call>' head so the
    tail is still inspectable; one rooted at a literal
    (``"a,b".split``) keeps '<const>' — methods on literals are always
    builtin; anything else is None."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        parts.append("<call>")
    elif isinstance(cur, (ast.Constant, ast.JoinedStr)):
        parts.append("<const>")
    else:
        return None
    return tuple(reversed(parts))


def _module_name(path: str, root: str) -> str:
    """Dotted module name relative to the scan root's PARENT, so the
    root directory's own name is the top package (seaweedfs_tpu/...,
    tools/..., or a fixture tree's top dir)."""
    base = os.path.dirname(os.path.abspath(root))
    rel = os.path.relpath(os.path.abspath(path), base)
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = rel.replace(os.sep, "/").split("/")
    if parts and parts[-1] == "__init__":
        parts.pop()
    return ".".join(p for p in parts if p not in ("..", "."))


def _property_alias(item: ast.AST) -> str | None:
    """'http' -> '_session' for the accessor idiom: an @property whose
    last statement is `return self.<attr>` (an assert guard before it
    is tolerated — shell/env.py's shape)."""
    if not isinstance(item, ast.FunctionDef):
        return None
    if not any(isinstance(d, ast.Name) and d.id == "property"
               for d in item.decorator_list):
        return None
    stmts = [s for s in item.body
             if not (isinstance(s, ast.Expr)
                     and isinstance(s.value, ast.Constant))]
    while stmts and isinstance(stmts[0], ast.Assert):
        stmts.pop(0)
    if len(stmts) == 1 and isinstance(stmts[0], ast.Return) \
            and isinstance(stmts[0].value, ast.Attribute) \
            and isinstance(stmts[0].value.value, ast.Name) \
            and stmts[0].value.value.id == "self":
        return stmts[0].value.attr
    return None


_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
               "Condition"}


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    tail = chain_of(value.func)
    return bool(tail) and tail[-1] in _LOCK_CTORS


class SymbolTable:
    """The whole-program index. Build once, share across passes."""

    def __init__(self, roots: list[str]):
        self.roots = [os.path.abspath(r) for r in roots]
        self.modules: dict[str, ModuleInfo] = {}
        self.by_rel: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.method_index: dict[str, list[FunctionInfo]] = {}
        self.class_index: dict[str, list[ClassInfo]] = {}

    # -- construction ---------------------------------------------------
    @classmethod
    def build(cls, roots: list[str]) -> "SymbolTable":
        table = cls(roots)
        for root in table.roots:
            for path in iter_py_files([root]):
                table._add_file(path, root)
        for mod in table.modules.values():
            for ci in mod.classes.values():
                table._harvest_attr_types(ci)
        return table

    def _add_file(self, path: str, root: str) -> None:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError):
            return                      # phase 1 reports syntax errors
        name = _module_name(path, root)
        mod = ModuleInfo(name, relpath(path), path, tree, src)
        self.modules[name] = mod
        self.by_rel[mod.rel] = mod
        for node in tree.body:
            self._index_top(mod, node)
        # function-level imports (the tree's cycle-avoidance idiom:
        # `from ..util.connpool import SyncHttpPool` inside __init__)
        # join the module maps — top-level bindings win on collision
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    key = alias.asname or alias.name.split(".")[0]
                    mod.imports.setdefault(
                        key, alias.name if alias.asname
                        else alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(mod, node)
                if base is not None:
                    for alias in node.names:
                        mod.from_symbols.setdefault(
                            alias.asname or alias.name,
                            (base, alias.name))

    def _index_top(self, mod: ModuleInfo, node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports[alias.asname or
                            alias.name.split(".")[0]] = (
                    alias.name if alias.asname else
                    alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            base = self._resolve_from(mod, node)
            if base is not None:
                for alias in node.names:
                    mod.from_symbols[alias.asname or alias.name] = (
                        base, alias.name)
        elif isinstance(node, _FUNC_NODES):
            fi = FunctionInfo(mod, None, node)
            mod.functions[node.name] = fi
            self._register(fi)
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(mod, node)
            mod.classes[node.name] = ci
            self.class_index.setdefault(ci.name, []).append(ci)
            for item in node.body:
                if isinstance(item, _FUNC_NODES):
                    fi = FunctionInfo(mod, ci, item)
                    ci.methods[item.name] = fi
                    self._register(fi)
                    alias = _property_alias(item)
                    if alias:
                        ci.prop_aliases[item.name] = alias
        elif isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    mod.lock_names.add(t.id)
        elif isinstance(node, (ast.If, ast.Try)):
            # metrics.py's `if HAVE_PROMETHEUS:` / try-import guards:
            # one level of conditional nesting is still "top level"
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, (ast.Import, ast.ImportFrom,
                                    *_FUNC_NODES, ast.ClassDef,
                                    ast.Assign)):
                    self._index_top(mod, sub)

    def _register(self, fi: FunctionInfo) -> None:
        self.functions[fi.qual] = fi
        self.method_index.setdefault(fi.name, []).append(fi)

    def _resolve_from(self, mod: ModuleInfo,
                      node: ast.ImportFrom) -> str | None:
        if node.level == 0:
            return node.module or ""
        pkg = mod.package
        for _ in range(node.level - 1):
            pkg = pkg.rpartition(".")[0]
        if node.module:
            return f"{pkg}.{node.module}" if pkg else node.module
        return pkg or None

    def _harvest_attr_types(self, ci: ClassInfo) -> None:
        """self.x = Ctor(...) anywhere in the class -> attr x has the
        ctor's (chain-string) type. Last assignment wins; a non-ctor
        reassignment poisons the entry (bounded honesty)."""
        for fi in ci.methods.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    if isinstance(value, ast.Call):
                        if any(k.arg and "timeout" in k.arg
                               and not (isinstance(k.value, ast.Constant)
                                        and k.value.value is None)
                               for k in value.keywords):
                            ci.timeout_attrs.add(t.attr)
                        resolved = self.resolve_class_chain(
                            fi, chain_of(value.func))
                        if resolved is not None:
                            ci.attr_types[t.attr] = resolved.qual
                            continue
                    ci.attr_types.pop(t.attr, None)

    # -- lookups --------------------------------------------------------
    def resolve_module(self, dotted: str) -> ModuleInfo | None:
        return self.modules.get(dotted)

    def class_by_qual(self, qual: str) -> ClassInfo | None:
        mod_name, _, cls_name = qual.rpartition(".")
        mod = self.modules.get(mod_name)
        return mod.classes.get(cls_name) if mod else None

    def iter_mro(self, ci: ClassInfo, _seen=None):
        """The class then its resolvable bases, depth-first, bounded
        by a visited set (diamonds/cycles terminate)."""
        seen = _seen if _seen is not None else set()
        if ci.qual in seen:
            return
        seen.add(ci.qual)
        yield ci
        for base in ci.bases:
            target = self._resolve_base(ci, base)
            if target is not None:
                yield from self.iter_mro(target, seen)

    def _resolve_base(self, ci: ClassInfo,
                      base: str) -> ClassInfo | None:
        mod = ci.module
        head, _, tail = base.partition(".")
        if not tail:                      # bare name: local or from-import
            if head in mod.classes:
                return mod.classes[head]
            fs = mod.from_symbols.get(head)
            if fs:
                target = self.modules.get(fs[0])
                if target:
                    return target.classes.get(fs[1])
            return None
        # dotted: alias.Class or package.module.Class
        alias = mod.imports.get(head)
        if alias:
            target = self.modules.get(f"{alias}.{tail}".rpartition(".")[0]
                                      if "." in tail else alias)
            if target:
                return target.classes.get(tail.rpartition(".")[2])
        fs = mod.from_symbols.get(head)
        if fs:                            # from a import b; class C(b.X)
            target = self.modules.get(f"{fs[0]}.{fs[1]}")
            if target:
                return target.classes.get(tail)
        return None

    def lookup_method(self, ci: ClassInfo,
                      name: str) -> FunctionInfo | None:
        for c in self.iter_mro(ci):
            if name in c.methods:
                return c.methods[name]
        return None

    def resolve_class_chain(self, fi: FunctionInfo,
                            chain: tuple[str, ...] | None
                            ) -> ClassInfo | None:
        """Resolve a constructor reference (`WeedClient`,
        `client.WeedClient`, `chunk_cache.TieredChunkCache`) to its
        ClassInfo from `fi`'s scope."""
        if not chain:
            return None
        mod = fi.module
        head = chain[0]
        if len(chain) == 1:
            if head in mod.classes:
                return mod.classes[head]
            fs = mod.from_symbols.get(head)
            if fs:
                target = self.modules.get(fs[0])
                if target and fs[1] in target.classes:
                    return target.classes[fs[1]]
            return None
        target_mod = self._module_of_head(mod, head)
        if target_mod is not None and len(chain) == 2:
            return target_mod.classes.get(chain[1])
        return None

    def _module_of_head(self, mod: ModuleInfo,
                        head: str) -> ModuleInfo | None:
        """What module does the name `head` refer to inside `mod`?"""
        fs = mod.from_symbols.get(head)
        if fs:
            sub = self.modules.get(f"{fs[0]}.{fs[1]}"
                                   if fs[0] else fs[1])
            if sub is not None:
                return sub
        alias = mod.imports.get(head)
        if alias:
            return self.modules.get(alias)
        return None
