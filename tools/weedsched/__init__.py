"""weedsched: deterministic interleaving explorer for the asyncio
protocol cores (the dynamic companion to weedlint's static
cancellation rules — see STATIC_ANALYSIS.md, "phase 3").

weedlint proves the SHAPE of cancellation safety (undo paired in a
finally, re-validation after an await); weedsched runs the real
protocol objects — RaftSequencer, ShardMap replay, TieredChunkCache,
FrameChannel, SingleFlight, the autopilot executor — under a
controlled event loop that permutes every scheduling decision from a
seed and injects CancelledError at each await point in turn, then
asserts the invariants the subsystems document (no duplicate fids,
exactly-once entries, no stale cache bytes, no leaked pending
futures). A violation prints a minimized schedule trace: the shortest
choice list found that still reproduces it.

Entry point: ``python -m tools.weedsched`` (``--quick`` is the CI
gate wired into tools/ci.sh under a WS_BUDGET_S wall-clock budget).
"""

from .loop import Chooser, SchedLoop  # noqa: F401
from .explore import explore_scenario, run_once  # noqa: F401
from .scenarios import SCENARIOS  # noqa: F401
