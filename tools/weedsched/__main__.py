"""weedsched CLI.

Exit codes: 0 every scenario matched its expectation (cores green,
fixtures detected) inside the budget; 1 a core violated / a fixture
went undetected / the wall-clock budget blew; 2 usage errors.

The JSON report (``--json``) is deterministic for a given seed list:
no wall-clock fields, sorted keys, stable ordering — byte-identical
across runs (asserted by tests/test_weedsched.py). Wall-clock/budget
accounting prints to stderr only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .explore import explore_scenario
from .fixtures import FIXTURES
from .scenarios import SCENARIOS

SEEDS_PATH = os.path.join(os.path.dirname(__file__), "seeds.json")
# quick-gate wall-clock budget (seconds), the WS_BUDGET_S of ci.sh
DEFAULT_BUDGET_S = 120.0


def _all_scenarios() -> dict:
    out = dict(SCENARIOS)
    out.update(FIXTURES)
    return out


def _load_seeds(mode: str) -> list[int]:
    with open(SEEDS_PATH) as f:
        corpus = json.load(f)
    return [int(s) for s in corpus[mode]]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.weedsched",
        description="deterministic interleaving explorer for the "
                    "asyncio protocol cores (see STATIC_ANALYSIS.md)")
    p.add_argument("--quick", action="store_true",
                   help="CI gate: checked-in quick seed corpus, stop "
                        "at the first violation per scenario, enforce "
                        "the WS_BUDGET_S wall-clock budget")
    p.add_argument("--scenario", action="append", default=None,
                   metavar="NAME",
                   help="run only this scenario (repeatable; default "
                        "all cores + fixtures)")
    p.add_argument("--seed", default="", metavar="N[,N...]",
                   help="explicit seeds (overrides the corpus)")
    p.add_argument("--no-inject", action="store_true",
                   help="schedule permutations only, no cancellation "
                        "injection")
    p.add_argument("--json", action="store_true",
                   help="print the deterministic JSON report to "
                        "stdout")
    p.add_argument("--list", action="store_true",
                   help="list scenarios and fixtures")
    p.add_argument("--budget", type=float, default=None, metavar="S",
                   help="wall-clock budget in seconds (default: "
                        "WS_BUDGET_S env or "
                        f"{DEFAULT_BUDGET_S:.0f}; enforced with "
                        "--quick)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    scns = _all_scenarios()
    if args.list:
        for name, s in sorted(scns.items()):
            tag = "fixture" if s.kind == "fixture" else "core"
            print(f"{name} [{tag}]: {s.description}")
        return 0
    if args.scenario:
        missing = [n for n in args.scenario if n not in scns]
        if missing:
            print(f"weedsched: unknown scenario(s): "
                  f"{', '.join(missing)}", file=sys.stderr)
            return 2
        scns = {n: scns[n] for n in args.scenario}
    try:
        seeds = [int(s) for s in args.seed.split(",") if s] \
            if args.seed else _load_seeds(
                "quick" if args.quick else "full")
    except (ValueError, KeyError, OSError) as e:
        print(f"weedsched: bad seeds: {e}", file=sys.stderr)
        return 2
    budget = args.budget if args.budget is not None else float(
        os.environ.get("WS_BUDGET_S", DEFAULT_BUDGET_S))

    # the cores log every leadership change / teardown; across
    # thousands of permuted runs that is pure stderr noise here
    from seaweedfs_tpu.util import glog
    glog._to_stderr = False

    t0 = time.monotonic()
    rows = []
    for name in sorted(scns):
        rows.append(explore_scenario(
            scns[name], seeds, inject=not args.no_inject,
            stop_on_first=args.quick))
    elapsed = time.monotonic() - t0

    report = {
        "version": 1,
        "mode": "quick" if args.quick else "full",
        "seeds": seeds,
        "scenarios": rows,
        "ok": all(r["ok"] for r in rows),
    }
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for r in rows:
            verdict = "ok" if r["ok"] else "FAIL"
            want = "must violate" if r["expect_violation"] \
                else "must hold"
            extra = " truncated" if r["truncated"] else ""
            print(f"{r['name']:<16} [{r['kind']}] {verdict:<4} "
                  f"({want}; runs={r['runs']} "
                  f"injections={r['injections']}{extra})")
            for v in r["violations"]:
                where = "baseline schedule" if v["victim"] is None \
                    else (f"cancel {v['victim']} at await point "
                          f"{v['inject_at']}")
                print(f"  seed {v['seed']}, {where}:")
                for e in v["errors"]:
                    print(f"    violation: {e}")
                print(f"    minimized schedule "
                      f"({len(v['schedule'])} of "
                      f"{v['schedule_len_original']} choices): "
                      f"{v['schedule']}")
                print(f"    trace: {' '.join(v['trace'][-40:])}")
    print(f"weedsched: {len(rows)} scenario(s), "
          f"{sum(r['runs'] for r in rows)} runs, "
          f"{sum(r['injections'] for r in rows)} injections "
          f"in {elapsed:.1f}s (budget {budget:.0f}s)",
          file=sys.stderr)
    if args.quick and elapsed > budget:
        print(f"weedsched: quick run blew its budget: {elapsed:.1f}s "
              f"> {budget:.0f}s — trim seeds.json or raise "
              f"WS_BUDGET_S", file=sys.stderr)
        return 1
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
