"""Drive scenarios under the controlled loop: schedule exploration,
CancelledError injection at every await point, invariant checking,
and coordinate-descent minimization of failing schedules.

A run is identified by (scenario, seed, victim, inject_at): the seed
fixes every scheduling choice, the victim/inject_at pair aims one
``task.cancel()`` at the victim's N-th resumption — exactly the
cancellation a disconnecting client or a timed-out ``wait_for``
delivers at that await point. Violations carry the full choice list;
the minimizer then replays with positions forced to 0 (run the first
runnable) while the violation persists, so the reported schedule is
the shortest divergence from FIFO that still reproduces the bug.

Nothing here reads the wall clock and the report dict is built from
sorted/deterministic collections only, so the JSON a seed produces is
byte-identical across runs — asserted by tests/test_weedsched.py.
"""

from __future__ import annotations

import asyncio
import asyncio.tasks
from dataclasses import dataclass, field

from .loop import Chooser, Installed, SchedError, SchedLoop

# SchedLoop tasks are the pure-python Task, which is NOT an instance
# of the C-accelerated asyncio.Task — ownership checks need both
_TASK_TYPES = (asyncio.tasks._PyTask, asyncio.Task)

# livelock backstop: a run that makes this many steps without settling
# is itself a finding, surfaced loudly instead of hanging CI
MAX_STEPS = 20_000
# post-completion callback drain (done-callbacks, cancelled cleanups)
MAX_DRAIN = 2_000
# per-victim, per-seed injection cap; exceeding it is reported as
# "truncated" in the scenario row — never silently
MAX_INJECTIONS = 48
# replay budget for one minimization (each replay is a full run)
MINIMIZE_BUDGET = 240


@dataclass
class RunResult:
    violations: list[str] = field(default_factory=list)
    schedule: list[int] = field(default_factory=list)
    trace: list[str] = field(default_factory=list)
    resumptions: dict[str, int] = field(default_factory=dict)


def _effective_seed(seed: int, victim: str | None,
                    inject_at: int | None) -> int:
    """Decorrelate injected runs from their baseline: with the raw
    seed, every injection run replays the baseline's choice prefix and
    a whole sweep explores only one schedule per seed. The derivation
    is stable (crc32, not the salted built-in hash) so replays and
    reports stay byte-identical."""
    if victim is None:
        return seed
    import zlib
    return (seed * 1_000_003 + 97 * (inject_at or 0)
            + zlib.crc32(victim.encode())) & 0x7FFFFFFF


def run_once(scn, seed: int, victim: str | None = None,
             inject_at: int | None = None,
             replay: list[int] | None = None,
             max_steps: int = MAX_STEPS) -> RunResult:
    """One complete scenario execution under one schedule."""
    chooser = Chooser(_effective_seed(seed, victim, inject_at),
                      replay=replay)
    loop = SchedLoop(chooser)
    with Installed(loop):
        run = scn.build()
        roots = [loop.create_task(coro, name=name)
                 for name, coro in run.tasks]
        trace, resumptions = _drive(loop, victim, inject_at, max_steps)
        violations: list[str] = []
        undone = sorted(t.get_name() for t in loop.tasks
                        if not t.done())
        if undone:
            violations.append(
                "deadlock: quiescent with unfinished tasks: "
                + ", ".join(undone))
            for t in loop.tasks:
                if not t.done():
                    t.cancel()
            _drain(loop, trace)
        for t in loop.tasks:
            if t.done() and not t.cancelled():
                exc = t.exception()
                if exc is not None:
                    violations.append(
                        f"task {t.get_name()} crashed: "
                        f"{type(exc).__name__}: {exc}")
        violations += loop.cb_errors
        violations += run.check()
        del roots
    return RunResult(violations=violations,
                     schedule=list(chooser.choices),
                     trace=trace, resumptions=resumptions)


def _drive(loop: SchedLoop, victim: str | None, inject_at: int | None,
           max_steps: int) -> tuple[list[str], dict[str, int]]:
    trace: list[str] = []
    resumptions: dict[str, int] = {}
    injected = False
    steps = 0
    while any(not t.done() for t in loop.tasks):
        h = loop.next_handle()
        if h is None:
            break                       # quiescent: checked by caller
        owner = getattr(getattr(h, "_callback", None), "__self__",
                        None)
        if isinstance(owner, _TASK_TYPES):
            name = owner.get_name()
            seen = resumptions.get(name, 0)
            if name == victim and inject_at is not None \
                    and seen == inject_at and not injected:
                # cancel RIGHT BEFORE the victim's chosen resumption:
                # the queued step then raises CancelledError into the
                # coroutine at exactly its current await point
                owner.cancel()
                injected = True
                trace.append(f"cancel!{name}")
            resumptions[name] = seen + 1
        else:
            name = "."                  # plain callback (done hooks,
            #                             timer releases, ...)
        trace.append(name)
        h._run()
        steps += 1
        if steps > max_steps:
            raise SchedError(
                f"livelock: {max_steps} steps without settling "
                f"(trace tail: {trace[-12:]})")
    _drain(loop, trace)
    return trace, resumptions


def _drain(loop: SchedLoop, trace: list[str]) -> None:
    """Run stray callbacks left after every task finished (done
    callbacks, cancellation cleanups) so no handle outlives the run."""
    for _ in range(MAX_DRAIN):
        h = loop.next_handle()
        if h is None:
            return
        trace.append("~")
        h._run()
    raise SchedError("drain did not settle within the step budget")


def minimize(scn, seed: int, victim: str | None, inject_at: int | None,
             schedule: list[int],
             budget: int = MINIMIZE_BUDGET) -> tuple[list[int],
                                                     RunResult]:
    """Coordinate descent toward the FIFO schedule: force one recorded
    choice at a time to 0 and keep the change while the run still
    violates. Returns the minimized choice list and its final run."""
    best = list(schedule)
    replays = 0
    improved = True
    while improved and replays < budget:
        improved = False
        for pos in range(len(best)):
            if best[pos] == 0:
                continue
            cand = best[:pos] + [0] + best[pos + 1:]
            replays += 1
            if run_once(scn, seed, victim=victim, inject_at=inject_at,
                        replay=cand).violations:
                best = cand
                improved = True
            if replays >= budget:
                break
    while best and best[-1] == 0:       # replay pads zeros back
        best.pop()
    final = run_once(scn, seed, victim=victim, inject_at=inject_at,
                     replay=best)
    if not final.violations:            # paranoia: never "minimize" a
        best = list(schedule)           # violation out of existence
        final = run_once(scn, seed, victim=victim,
                         inject_at=inject_at, replay=best)
    return best, final


def explore_scenario(scn, seeds: list[int], inject: bool = True,
                     stop_on_first: bool = False,
                     max_injections: int = MAX_INJECTIONS,
                     minimize_budget: int = MINIMIZE_BUDGET) -> dict:
    """Full sweep of one scenario: a baseline run per seed, then (for
    declared victims) one injected run per await point. Returns a
    deterministic report row."""
    row = {
        "name": scn.name,
        "kind": scn.kind,
        "expect_violation": scn.expect_violation,
        "seeds": list(seeds),
        "runs": 0,
        "injections": 0,
        "truncated": False,
        "violations": [],
    }

    def record(seed, victim, inject_at, res):
        sched, final = minimize(scn, seed, victim, inject_at,
                                res.schedule, budget=minimize_budget)
        row["violations"].append({
            "seed": seed,
            "victim": victim,
            "inject_at": inject_at,
            "errors": final.violations,
            "schedule": sched,
            "schedule_len_original": len(res.schedule),
            "trace": final.trace,
        })

    done = False
    for seed in seeds:
        base = run_once(scn, seed)
        row["runs"] += 1
        if base.violations:
            record(seed, None, None, base)
            if stop_on_first:
                done = True
        if done:
            break
        if not inject:
            continue
        for victim in scn.victims:
            total = base.resumptions.get(victim, 0)
            if total > max_injections:
                row["truncated"] = True
                total = max_injections
            for i in range(total):
                res = run_once(scn, seed, victim=victim, inject_at=i)
                row["runs"] += 1
                row["injections"] += 1
                if res.violations:
                    record(seed, victim, i, res)
                    if stop_on_first:
                        done = True
                        break
            if done:
                break
        if done:
            break
    row["detected"] = bool(row["violations"])
    row["ok"] = row["detected"] == scn.expect_violation
    return row
