"""Seeded known-bug fixtures: the two historical concurrency bugs,
re-introduced in mini-classes the explorer MUST detect.

These are the dynamic twins of the weedlint phase-3 fixture trees —
the same bug shapes, alive. Each carries a ``weedlint: ignore``
suppression naming itself a seeded fixture: that keeps the enforced
tree's baseline empty while PROVING (via the unused-suppression rule)
that the static side still flags the shape — if a rule regression
stopped firing here, the suppression would go stale and fail the
lint gate.

* ``pending-leak`` — the FrameChannel ``_request`` bug fixed in this
  tree: a pending-table registration whose pop lives on the straight
  path only, so a caller cancelled between registration and response
  leaks the entry forever (the reader loop then counts a phantom
  in-flight request against its timeout accounting).
* ``gen-fence`` — the TieredChunkCache shape before fill tokens: a
  read-check / await / write with no re-validation, so a fill that
  raced an overwrite installs stale bytes under the new generation.

Both must fail under exploration with a minimized schedule; a green
run here means the explorer lost its teeth (tests assert detection).
"""

from __future__ import annotations

import asyncio

from .scenarios import Run, Scenario


class LeakyPendingTable:
    """The pre-fix ``FrameChannel._request`` shape: pop only on the
    straight-line path, never in a ``finally``."""

    def __init__(self):
        self.pending: dict[int, asyncio.Future] = {}

    async def request(self, rid: int) -> None:
        fut = asyncio.get_running_loop().create_future()
        self.pending[rid] = fut  # weedlint: ignore[cancel-leak] seeded known-bug fixture: weedsched must detect this leak dynamically; the suppression going stale means the static rule lost it too
        await asyncio.sleep(0)          # the wire round trip
        if not fut.done():
            fut.set_result(None)        # the peer answers
        await fut
        self.pending.pop(rid, None)     # never reached when cancelled


def _pending_leak() -> Run:
    tbl = LeakyPendingTable()

    async def req(i: int) -> None:
        await tbl.request(i)

    def check() -> list:
        if tbl.pending:
            return [f"leaked pending entries: {sorted(tbl.pending)}"]
        return []

    return Run(tasks=[("req-1", req(1)), ("req-2", req(2))],
               check=check)


class UnfencedCache:
    """The pre-token cache-fill shape: the presence check is not
    re-validated after the fetch await, so a racing invalidation is
    overwritten by stale bytes."""

    def __init__(self, source: dict):
        self.data: dict[str, bytes] = {}
        self.source = source

    async def fill(self, key: str) -> None:
        if key not in self.data:
            stale = self.source[key]
            await asyncio.sleep(0)      # the network fetch
            self.data[key] = stale  # weedlint: ignore[await-atomicity] seeded known-bug fixture: weedsched must detect the stale fill dynamically; the suppression going stale means the static rule lost it too


def _gen_fence() -> Run:
    source = {"k": b"v1"}
    cache = UnfencedCache(source)

    async def filler() -> None:
        for _ in range(2):
            await cache.fill("k")
            await asyncio.sleep(0)

    async def overwrite() -> None:
        await asyncio.sleep(0)
        # new generation lands and invalidates, atomically
        source["k"] = b"v2"
        cache.data.pop("k", None)

    def check() -> list:
        got = cache.data.get("k")
        if got is not None and got != source["k"]:
            return [f"stale bytes {got!r} cached over newest "
                    f"{source['k']!r}"]
        return []

    return Run(tasks=[("fill", filler()), ("fill-2", filler()),
                      ("overwrite", overwrite())],
               check=check)


FIXTURES: dict[str, Scenario] = {
    "pending-leak": Scenario(
        "pending-leak", _pending_leak, victims=("req-1", "req-2"),
        kind="fixture", expect_violation=True,
        description="pending-table registration with no finally: a "
                    "cancelled requester must leak the entry"),
    "gen-fence": Scenario(
        "gen-fence", _gen_fence, victims=("fill", "fill-2"),
        kind="fixture", expect_violation=True,
        description="un-fenced read-check/await/write fill: some "
                    "interleaving must install stale bytes"),
}
