"""The controlled event loop: every scheduling decision is a recorded
choice, time is virtual, and a whole run replays from a seed.

``SchedLoop`` implements just enough of the asyncio event-loop surface
for the pure-python task/future/lock machinery to run on it —
``call_soon``/``call_later``/``call_at``/``time``/``create_future``/
``create_task`` plus the handle-cancellation hooks. It is driven
synchronously by the explorer (never ``run_forever``): whenever more
than one callback is runnable, a seeded :class:`Chooser` picks which
runs next and records the pick, so a schedule IS a replayable list of
small integers. Timers advance virtual time only when the ready queue
drains, so a 30s ``wait_for`` deadline costs nothing and a run's
timing is a pure function of its choices.

Deliberately pinned to CPython's pure-python asyncio internals
(``asyncio.tasks._PyTask`` so task step callbacks expose ``__self__``
for ownership, ``Handle._callback``/``Handle._cancelled`` for
dispatch) — the C accelerated Task hides the callback's bound self,
which the explorer needs to attribute steps to tasks and to aim
cancellation injection. Verified against 3.10; guarded imports keep
failures loud, not silent.
"""

from __future__ import annotations

import asyncio
import asyncio.events
import asyncio.tasks
import heapq
import random

# the pure-python Task: its __step/__wakeup callbacks are bound
# methods, so Handle._callback.__self__ identifies the owning task
_PyTask = asyncio.tasks._PyTask


class SchedError(Exception):
    """Explorer-internal failure (livelock backstop, replay misuse) —
    distinct from an invariant violation in the scenario under test."""


class Chooser:
    """Source of scheduling decisions: seeded-random when exploring,
    scripted when replaying a recorded (possibly minimized) schedule.

    ``choices`` accumulates every pick either way, so a fresh random
    run hands the explorer exactly the list it needs to replay."""

    def __init__(self, seed: int = 0, replay: list[int] | None = None):
        self.seed = seed
        self._rng = random.Random(seed)
        self._replay = list(replay) if replay is not None else None
        self.choices: list[int] = []

    def choose(self, n: int) -> int:
        if n <= 0:
            raise SchedError("choose() with an empty ready queue")
        if self._replay is not None:
            pos = len(self.choices)
            # past the recorded tail (minimization trims it): first
            # runnable — the canonical "0" the minimizer drives toward
            i = self._replay[pos] if pos < len(self._replay) else 0
            i = min(max(i, 0), n - 1)
        else:
            i = self._rng.randrange(n)
        self.choices.append(i)
        return i


class SchedLoop:
    """Minimal deterministic event loop; see the module docstring."""

    def __init__(self, chooser: Chooser):
        self._chooser = chooser
        self._ready: list[asyncio.Handle] = []
        self._timers: list[tuple[float, int, asyncio.TimerHandle]] = []
        self._tie = 0               # heap tie-break: insertion order
        self._now = 0.0             # virtual seconds
        self._closed = False
        self._task_seq = 0
        self.tasks: list[asyncio.Task] = []   # every task ever created
        self.cb_errors: list[str] = []        # callback exceptions

    # ---- surface the task/future/lock machinery calls ----

    def get_debug(self) -> bool:
        return False

    def is_running(self) -> bool:
        return True

    def is_closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True

    def time(self) -> float:
        return self._now

    def call_soon(self, callback, *args, context=None) -> asyncio.Handle:
        h = asyncio.Handle(callback, args, self, context)
        self._ready.append(h)
        return h

    # same-thread by construction: the explorer never leaves the
    # driving thread, so threadsafe wakeups are plain wakeups
    call_soon_threadsafe = call_soon

    def call_later(self, delay, callback, *args, context=None):
        return self.call_at(self._now + max(0.0, float(delay)),
                            callback, *args, context=context)

    def call_at(self, when, callback, *args, context=None):
        h = asyncio.TimerHandle(float(when), callback, args, self,
                                context)
        self._tie += 1
        heapq.heappush(self._timers, (float(when), self._tie, h))
        h._scheduled = True
        return h

    def _timer_handle_cancelled(self, handle) -> None:
        # lazily dropped when popped; the heap entry is just skipped
        pass

    def create_future(self) -> asyncio.Future:
        return asyncio.Future(loop=self)

    def create_task(self, coro, *, name=None) -> asyncio.Task:
        # explicit deterministic default names: _PyTask's Task-<n>
        # fallback counts GLOBALLY across runs, which would leak run
        # ordering into schedule traces and break byte-identical
        # reports for a given seed
        self._task_seq += 1
        task = _PyTask(coro, loop=self,
                       name=name or f"t{self._task_seq}")
        self.tasks.append(task)
        return task

    def call_exception_handler(self, context: dict) -> None:
        # handle-callback crashes are deterministic and gate the run;
        # future/task __del__ reports arrive at GC time and must not
        # (they are the only nondeterministic entry into this hook)
        if "handle" in context:
            exc = context.get("exception")
            self.cb_errors.append(
                f"{context.get('message', 'callback error')}: "
                f"{type(exc).__name__ if exc else '?'}: {exc}")

    def default_exception_handler(self, context: dict) -> None:
        self.call_exception_handler(context)

    # ---- explorer-side stepping ----

    def runnable(self) -> bool:
        return any(not h._cancelled for h in self._ready) \
            or any(not h._cancelled for _, _, h in self._timers)

    def next_handle(self) -> asyncio.Handle | None:
        """Pick (via the chooser) and remove the next handle to run;
        advances virtual time to the earliest timer when the ready
        queue is empty. None means quiescent."""
        self._ready = [h for h in self._ready if not h._cancelled]
        if not self._ready:
            self._advance_timers()
        if not self._ready:
            return None
        if len(self._ready) == 1:
            # a forced move is not a decision: keeping it out of the
            # schedule makes recorded traces short and minimization
            # meaningful
            return self._ready.pop(0)
        return self._ready.pop(self._chooser.choose(len(self._ready)))

    def _advance_timers(self) -> None:
        while self._timers and not self._ready:
            when, _, h = heapq.heappop(self._timers)
            if h._cancelled:
                continue
            self._now = max(self._now, when)
            self._ready.append(h)
            # everything due at the same virtual instant becomes one
            # scheduling decision, not a fixed heap order
            while self._timers and self._timers[0][0] <= self._now:
                _, _, h2 = heapq.heappop(self._timers)
                if not h2._cancelled:
                    self._ready.append(h2)


class Installed:
    """Context manager: make `loop` the running loop for the calling
    thread so ``get_running_loop()``-based code (futures, locks,
    ensure_future) lands on it, without touching the event loop
    policy."""

    def __init__(self, loop: SchedLoop):
        self.loop = loop
        self._prev = None

    def __enter__(self) -> SchedLoop:
        self._prev = asyncio.events._get_running_loop()
        if self._prev is not None:
            raise SchedError(
                "weedsched cannot run inside a running event loop")
        asyncio.events._set_running_loop(self.loop)
        return self.loop

    def __exit__(self, *exc) -> None:
        asyncio.events._set_running_loop(self._prev)
