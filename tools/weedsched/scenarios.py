"""Tagged async scenarios: the REAL protocol cores, in-process, under
the controlled loop, each declaring the invariant its subsystem
documents.

Every scenario here is expected GREEN — a violation under any
schedule or injection is a real concurrency bug in the tree (the two
historical bug shapes that motivated the explorer live in
``fixtures.py``, re-introduced in mini-classes, and MUST be caught).

Scenario contract: ``build()`` returns a :class:`Run` whose ``tasks``
are ``(name, coroutine)`` pairs started as named root tasks and whose
``check()`` runs after the loop settles, returning violation strings
(empty = invariants held). ``victims`` names the root tasks whose
await points get CancelledError injected one at a time — the tasks a
disconnecting client or timeout would cancel in production. Scenario
code never reads the wall clock; sleeps ride the loop's virtual time.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Callable

# allocation attempts per client in the raft scenario — bounds the
# reserve/retry loop so a livelock shows up as a deadlock finding,
# not a step-budget crash
_ALLOC_TRIES = 60


@dataclass
class Run:
    tasks: list = field(default_factory=list)
    check: Callable[[], list] = lambda: []


class Scenario:
    def __init__(self, name: str, build, victims: tuple = (),
                 kind: str = "core", expect_violation: bool = False,
                 description: str = ""):
        self.name = name
        self.build = build
        self.victims = victims
        self.kind = kind
        self.expect_violation = expect_violation
        self.description = description


SCENARIOS: dict[str, Scenario] = {}


def scenario(name: str, victims: tuple = (), description: str = ""):
    def deco(build):
        SCENARIOS[name] = Scenario(name, build, victims=victims,
                                   description=description)
        return build
    return deco


# ---- raft sequencer: reserve/apply vs deposition ---------------------

PEERS = ["a:1", "b:2", "c:3"]


@scenario("raft-sequencer", victims=("alloc-a", "alloc-b"),
          description="two masters allocate fids across a mid-run "
                      "deposition; no fid may ever be issued twice")
def _raft_sequencer() -> Run:
    from seaweedfs_tpu.master.election import Election
    from seaweedfs_tpu.master.sequence import (MemorySequencer,
                                               RaftSequencer,
                                               SequenceBehind)

    a = Election("a:1", PEERS)
    a.role = Election.LEADER
    a.leader = a.me
    a.term = 1

    async def round_a() -> int:
        # a quorum round is a suspension point; acks only count while
        # this node still leads (the real round checks the same)
        await asyncio.sleep(0)
        if a.is_leader:
            a.commit = a.last_index()
            a._apply_committed()
        return 3

    a._replicate_round = round_a
    seq_a = RaftSequencer(MemorySequencer(), a, step=8)

    b = Election("b:2", PEERS)
    seq_b = RaftSequencer(MemorySequencer(), b, step=8)

    issued: dict[str, list] = {"a": [], "b": []}
    deposed = {"done": False}

    async def alloc(seq, out, n: int) -> None:
        for _ in range(_ALLOC_TRIES):
            if len(out) >= n:
                return
            try:
                out.append(seq.next_file_id())
            except SequenceBehind:
                if not await seq.reserve(1):
                    return          # deposed: the caller redirects
            await asyncio.sleep(0)

    async def depose() -> None:
        for _ in range(3):
            await asyncio.sleep(0)
        # the quorum contract, in one atomic step (no awaits): B holds
        # everything A's commits certified, then A observes the higher
        # term and B promotes
        r = b.on_append(1, "a:1", 0, 0, list(a.entries), a.commit)
        if not r.get("ok"):
            raise RuntimeError(f"log transfer refused: {r}")
        a._adopt_higher_term(2)
        b.role = Election.LEADER
        b.leader = b.me
        b.term = 2

        async def round_b() -> int:
            await asyncio.sleep(0)
            if b.is_leader:
                b.commit = b.last_index()
                b._apply_committed()
            return 3

        b._replicate_round = round_b
        deposed["done"] = True

    async def alloc_b() -> None:
        while not deposed["done"]:
            await asyncio.sleep(0)
        await alloc(seq_b, issued["b"], 6)

    def check() -> list:
        v = []
        for who, ids in sorted(issued.items()):
            if len(set(ids)) != len(ids):
                v.append(f"duplicate fids within {who}: {sorted(ids)}")
        cross = set(issued["a"]) & set(issued["b"])
        if cross:
            v.append(f"fid issued by BOTH masters: {sorted(cross)}")
        return v

    return Run(tasks=[("alloc-a", alloc(seq_a, issued["a"], 6)),
                      ("depose", depose()),
                      ("alloc-b", alloc_b())],
               check=check)


# ---- shard map: journaled ops, replicated replay ---------------------

@scenario("shard-replay", victims=("apply-1", "apply-2"),
          description="two replicas replay the committed op journal "
                      "(with a duplicate delivery) at their own pace; "
                      "they must converge to one map")
def _shard_replay() -> Run:
    from seaweedfs_tpu.filer.shard import ShardMap, apply_map_op

    ops = [
        {"op": "set", "rules": [["/", 0], ["/a", 1]],
         "owners": {0: "f0:1", 1: "f1:1"}},
        {"op": "register", "shard": 2, "url": "f2:1"},
        {"op": "split_intent", "prefix": "/a/hot", "to": 2, "by": "op"},
        # duplicate delivery of the same intent: executors re-submit
        # after a crash and the transition must be idempotent
        {"op": "split_intent", "prefix": "/a/hot", "to": 2, "by": "op"},
        {"op": "commit_move", "id": "split:/a/hot"},
        {"op": "rename_intent", "src": "/a/x", "dst": "/b/y"},
        {"op": "commit_move", "id": "rename:/a/x:/b/y"},
    ]
    log: list = []
    replicas = [{"m": ShardMap(), "applied": 0},
                {"m": ShardMap(), "applied": 0}]

    async def propose() -> None:
        for op in ops:
            await asyncio.sleep(0)
            log.append(op)

    async def applier(r: dict) -> None:
        while r["applied"] < len(ops):
            if r["applied"] < len(log):
                # apply_map_op is pure (copy-on-write), so a replica
                # can never observe a half-applied transition
                r["m"] = apply_map_op(r["m"], log[r["applied"]])
                r["applied"] += 1
            await asyncio.sleep(0)

    def check() -> list:
        finals = []
        for r in replicas:
            m = r["m"]
            # crash-replay: a cancelled applier resumes from its
            # journal position — exactly what the executor does
            for op in log[r["applied"]:]:
                m = apply_map_op(m, op)
            finals.append(m.to_dict())
        v = []
        if finals[0] != finals[1]:
            v.append(f"replicas diverged: {finals[0]} != {finals[1]}")
        probe = finals[0] and ShardMap.from_dict(finals[0])
        for path in ("/a/hot/x", "/a/x", "/b/y", "/other"):
            s1 = ShardMap.from_dict(finals[0]).route(path)
            s2 = ShardMap.from_dict(finals[1]).route(path)
            if s1 != s2:
                v.append(f"{path} routes to {s1} vs {s2}")
        del probe
        return v

    return Run(tasks=[("propose", propose()),
                      ("apply-1", applier(replicas[0])),
                      ("apply-2", applier(replicas[1]))],
               check=check)


# ---- chunk cache: fenced fill vs invalidate --------------------------

@scenario("chunk-cache", victims=("fill-1", "fill-2"),
          description="concurrent fetch+fill against overwrite "
                      "invalidations; the cache must never serve "
                      "bytes older than the newest overwrite")
def _chunk_cache() -> Run:
    from seaweedfs_tpu.util.chunk_cache import TieredChunkCache

    cache = TieredChunkCache(mem_bytes=1 << 20, name="weedsched")
    src = {"v": 1}

    def body(v: int) -> bytes:
        return b"gen-%d" % v

    async def filler() -> None:
        for _ in range(3):
            token = cache.fill_token("fid")
            v = src["v"]
            await asyncio.sleep(0)      # the network fetch window
            await asyncio.sleep(0)
            cache.set_if("fid", body(v), token)
            await asyncio.sleep(0)

    async def overwrite() -> None:
        for _ in range(2):
            await asyncio.sleep(0)
            # bump + invalidate with no await between: one overwrite
            src["v"] += 1
            cache.delete("fid")
            await asyncio.sleep(0)

    def check() -> list:
        got = cache.get("fid")
        if got is not None and got != body(src["v"]):
            return [f"stale cache bytes {got!r}; newest overwrite is "
                    f"{body(src['v'])!r}"]
        return []

    return Run(tasks=[("fill-1", filler()), ("fill-2", filler()),
                      ("overwrite", overwrite())],
               check=check)


# ---- frame channel: multiplexed requests vs a severed wire -----------

class _FakeWriter:
    """In-memory peer-side of the wire: collects written frames for
    the responder task; close() severs it."""

    def __init__(self):
        self.buf = bytearray()
        self.closed = False

    def write(self, b: bytes) -> None:
        if self.closed:
            raise ConnectionResetError("wire severed")
        self.buf += b

    async def drain(self) -> None:
        await asyncio.sleep(0)
        if self.closed:
            raise ConnectionResetError("wire severed")

    def close(self) -> None:
        self.closed = True


@scenario("frame-channel", victims=("req-1", "req-2"),
          description="multiplexed requests over one channel while "
                      "the wire is severed mid-flight; no pending "
                      "entry, window slot or waiter may leak")
def _frame_channel() -> Run:
    from seaweedfs_tpu.util.frame import (RESP, FrameChannel,
                                          FrameChannelError,
                                          FrameDecoder, FrameFallback,
                                          encode_frame)

    chan = FrameChannel(target="peer:1", request_timeout=5.0)
    w = _FakeWriter()
    chan._writer = w
    chan._cwnd = 1.0        # window of 1: every extra request queues
    #                         in _acquire_slot, the leak-prone path
    chan._retry_at = 1e9    # no real reconnects: a severed writer
    #                         fails fast instead of opening sockets
    dec = FrameDecoder()
    results: dict[int, int] = {}

    async def peer() -> None:
        while not w.closed:
            if w.buf:
                frames = dec.feed(bytes(w.buf))
                del w.buf[:]
                for fr in frames:
                    rdec = FrameDecoder()
                    wire = encode_frame(RESP, fr.req_id, {"s": 200},
                                        b"ok")
                    for resp in rdec.feed(wire):
                        chan._dispatch(resp)
            await asyncio.sleep(0)

    async def req(i: int) -> None:
        try:
            status, _, _ = await chan.request("GET", f"/p{i}")
            results[i] = status
        except (FrameChannelError, FrameFallback):
            results[i] = -1     # downgrade path: legal under a sever

    async def sever() -> None:
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        chan._teardown(w, FrameChannelError("peer severed"))

    def check() -> list:
        v = []
        if chan._pending:
            v.append(f"leaked pending entries: "
                     f"{sorted(chan._pending)}")
        if chan._inflight != 0:
            v.append(f"congestion slots leaked: "
                     f"inflight={chan._inflight} after settle")
        if chan._win_waiters:
            v.append(f"leaked window waiters: "
                     f"{len(chan._win_waiters)}")
        return v

    return Run(tasks=[("req-1", req(1)), ("req-2", req(2)),
                      ("req-3", req(3)), ("peer", peer()),
                      ("sever", sever())],
               check=check)


# ---- singleflight: leader cancellation must not abort followers ------

@scenario("singleflight", victims=("caller-0", "caller-1"),
          description="collapsed concurrent calls; cancelling any "
                      "caller (the round leader included) must not "
                      "abort the shared work under the others")
def _singleflight() -> Run:
    from seaweedfs_tpu.util.singleflight import SingleFlight

    sf = SingleFlight()
    results: dict[int, object] = {}

    async def work():
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        return 42

    async def caller(i: int) -> None:
        results[i] = await sf.do("k", work)

    def check() -> list:
        v = []
        if sf._inflight:
            v.append(f"settled round never forgotten: "
                     f"{sorted(sf._inflight)}")
        for i, r in sorted(results.items()):
            if r != 42:
                v.append(f"caller-{i} saw {r!r} instead of the "
                         f"shared result")
        return v

    return Run(tasks=[("caller-0", caller(0)),
                      ("caller-1", caller(1)),
                      ("caller-2", caller(2))],
               check=check)


# ---- autopilot executor: plan dispatch vs deposition -----------------

@scenario("autopilot", victims=("cycle",),
          description="a repair plan executing while leadership is "
                      "lost mid-queue; halted actions never dispatch, "
                      "nothing dispatches twice, in_flight drains")
def _autopilot() -> Run:
    from seaweedfs_tpu.autopilot.execute import Executor
    from seaweedfs_tpu.autopilot.plan import KIND_REPLICATE, Action

    state = {"leader": True}
    posts: dict[str, int] = {}
    res: dict = {"rows": None}

    async def node_post(url, path, params, timeout_s=0.0):
        vid = str(params.get("volume", "?"))
        posts[vid] = posts.get(vid, 0) + 1
        await asyncio.sleep(0)
        return {}

    ex = Executor(node_post, mbps=1.0, concurrency=2,
                  is_leader=lambda: state["leader"])
    actions = [Action(kind=KIND_REPLICATE, vid=i, target="t:1",
                      targets=("t:1",), holders=("src:1",),
                      bytes_est=0, reason="weedsched")
               for i in range(1, 5)]

    async def cycle() -> None:
        res["rows"] = await ex.execute(actions)

    async def depose() -> None:
        for _ in range(3):
            await asyncio.sleep(0)
        state["leader"] = False

    def check() -> list:
        v = []
        if ex.in_flight:
            v.append(f"executor in_flight leaked: "
                     f"{sorted(ex.in_flight)}")
        for vid, n in sorted(posts.items()):
            if n > 1:
                v.append(f"action vid={vid} dispatched {n}x")
        rows = res["rows"]
        if rows is None:
            return v            # cycle was cancelled before settling
        if any(r is None for r in rows):
            v.append("execute() returned an unfilled result row")
            return v
        statuses = [r["status"] for r in rows]
        bad = [s for s in statuses if s not in ("ok", "halted")]
        if bad:
            v.append(f"unexpected action statuses: {bad}")
        halted = False
        for r in rows:
            if r["status"] == "halted":
                halted = True
                if posts.get(str(r["action"]["vid"])):
                    v.append(f"halted action vid="
                             f"{r['action']['vid']} was dispatched "
                             f"anyway")
            elif halted and r["status"] == "ok":
                v.append("action admitted after a halted predecessor")
        return v

    return Run(tasks=[("cycle", cycle()), ("depose", depose())],
               check=check)
