"""~30s data-plane wire smoke for tools/ci.sh.

Boots a REAL master + single-worker volume server as CLI processes and
drives the unified wire end to end over raw sockets:

  1. group-commit write burst — concurrent POSTs to one volume, all
     acked, /status shows coalesced batches;
  2. batch GET round trip — hot + cold + missing fids, order and bytes
     verified against single GETs;
  3. sendfile read — a large cold needle byte-verified against the
     buffered path, Range resume included;
  4. binary frame hop — a second master + `-workers 2` volume fleet:
     single reads and one cross-partition /batch driven over the
     frame protocol, byte-equal with the SAME requests over HTTP,
     with the sibling frame channels asserted in use via /status.

Data-plane regressions fail here in seconds, before tier-1 runs.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
PORT = int(os.environ.get("SWTPU_SMOKE_PORT", "21950"))


def wait_assign(master: str, tries: int = 60) -> None:
    for _ in range(tries):
        try:
            with urllib.request.urlopen(
                    f"http://{master}/dir/assign", timeout=3) as r:
                if b"fid" in r.read():
                    return
        except OSError:
            pass
        time.sleep(0.5)
    raise RuntimeError("cluster never became assignable")


def req(vol: str, method: str, path: str, body: bytes = b""
        ) -> "tuple[int, dict, bytes]":
    host, _, port = vol.rpartition(":")
    c = http.client.HTTPConnection(host, int(port), timeout=20)
    try:
        c.request(method, path, body=body or None)
        r = c.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        c.close()


def assign(master: str) -> dict:
    with urllib.request.urlopen(f"http://{master}/dir/assign",
                                timeout=5) as r:
        return json.load(r)


def main() -> int:
    from seaweedfs_tpu.util.batchframe import parse_all

    tmp = tempfile.mkdtemp(prefix="swtpu_wire_smoke_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    master = f"127.0.0.1:{PORT}"
    vol = f"127.0.0.1:{PORT + 1}"
    procs: list[subprocess.Popen] = []

    def spawn(*args: str) -> None:
        log = open(os.path.join(tmp, f"proc{len(procs)}.log"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu.cli", *args],
            stdout=log, stderr=subprocess.STDOUT, env=env, cwd=tmp))

    try:
        spawn("master", "-port", str(PORT), "-mdir",
              os.path.join(tmp, "m"), "-pulseSeconds", "1")
        time.sleep(1.5)
        spawn("volume", "-port", str(PORT + 1), "-dir",
              os.path.join(tmp, "v"), "-max", "10", "-master", master,
              "-pulseSeconds", "1", "-groupcommit.ms", "2")
        wait_assign(master)

        # -- 1. group-commit write burst --------------------------------
        assigns = [assign(master) for _ in range(16)]
        bodies = {a["fid"]: f"gc-{i}-".encode() * 40
                  for i, a in enumerate(assigns)}
        errs: list[str] = []

        def put(a: dict) -> None:
            st, _, out = req(a["url"], "POST", "/" + a["fid"],
                             bodies[a["fid"]])
            if st != 201:
                errs.append(f"POST {a['fid']}: {st} {out[:120]!r}")

        threads = [threading.Thread(target=put, args=(a,))
                   for a in assigns]
        for th in threads:
            th.start()
        for th in threads:
            th.join(20)
        assert not errs, errs
        st, _, out = req(vol, "GET", "/status")
        gc = json.loads(out).get("group_commit", {})
        assert gc.get("appended", 0) >= 16, gc
        print(f"  group commit: 16/16 concurrent writes acked, "
              f"batches={gc.get('batches')} max_batch="
              f"{gc.get('max_batch')}")

        # -- 2. batch GET round trip ------------------------------------
        fids = [a["fid"] for a in assigns[:6]]
        missing = fids[0].split(",")[0] + ",ffffffffdeadbeef"
        ask = fids[:3] + [missing] + fids[3:]
        st, hdrs, raw = req(vol, "GET", "/batch?fids=" + ",".join(ask))
        assert st == 200, (st, raw[:200])
        rows = parse_all(raw)
        assert [m["fid"] for m, _ in rows] == ask
        ok = 0
        for meta, got in rows:
            if meta["fid"] == missing:
                assert meta["status"] == 404, meta
            else:
                assert meta["status"] == 200, meta
                assert got == bodies[meta["fid"]], meta["fid"]
                ok += 1
        print(f"  batch GET: {ok} needles + 1 expected 404 in one "
              f"round trip, order preserved")

        # -- 3. sendfile cold read --------------------------------------
        big = assign(master)
        payload = bytes((i * 131 + 17) % 256 for i in range(300_000))
        st, _, _ = req(big["url"], "POST", "/" + big["fid"], payload)
        assert st == 201
        st, hdrs, got = req(vol, "GET", "/" + big["fid"])
        assert st == 200 and got == payload, \
            f"sendfile body mismatch ({len(got)}/{len(payload)})"
        c = http.client.HTTPConnection("127.0.0.1", PORT + 1,
                                       timeout=20)
        try:
            c.request("GET", "/" + big["fid"],
                      headers={"Range": "bytes=250000-"})
            r = c.getresponse()
            tail = r.read()
            assert r.status == 206 and tail == payload[250000:]
        finally:
            c.close()
        print(f"  sendfile: {len(payload)}-byte cold body + ranged "
              f"resume byte-verified over the raw listener")

        # -- 4. binary frame hop on a -workers 2 fleet ------------------
        m2 = f"127.0.0.1:{PORT + 2}"
        v2 = f"127.0.0.1:{PORT + 3}"
        spawn("master", "-port", str(PORT + 2), "-mdir",
              os.path.join(tmp, "m2"), "-pulseSeconds", "1")
        time.sleep(1.5)
        spawn("volume", "-port", str(PORT + 3), "-dir",
              os.path.join(tmp, "v2"), "-max", "10", "-master", m2,
              "-pulseSeconds", "1", "-workers", "2")
        wait_assign(m2)
        # grow past one volume so assigns cover BOTH vid-parity
        # partitions (vid % 2 owns the worker)
        with urllib.request.urlopen(f"http://{m2}/vol/grow?count=4",
                                    timeout=10) as r:
            r.read()
        fleet_fids: dict = {}
        vids = set()
        for i in range(32):
            a = assign(m2)
            vid = int(a["fid"].split(",")[0])
            body = f"frame-hop-{i}-".encode() * 50
            st, _, out = req(a["url"], "POST", "/" + a["fid"], body)
            assert st == 201, (st, out[:120])
            fleet_fids[a["fid"]] = body
            vids.add(vid % 2)
            if len(fleet_fids) >= 4 and len(vids) == 2:
                break
        assert len(vids) == 2, "assigns never covered both partitions"

        import asyncio

        async def frame_phase() -> None:
            from seaweedfs_tpu.util.frame import FrameChannel
            ch = FrameChannel(target=v2)
            try:
                # single reads over frames: whichever worker accepted
                # the connection forwards other-parity vids over its
                # sibling frame channel — byte-equal with HTTP
                for fid, want in fleet_fids.items():
                    fst, _, fbody = await ch.request("GET", "/" + fid)
                    hst, _, hbody = req(v2, "GET", "/" + fid)
                    assert fst == hst == 200, (fid, fst, hst)
                    assert fbody == hbody == want, fid
                # one cross-partition batch over frames vs HTTP
                ask = ",".join(fleet_fids)
                fst, _, fraw = await ch.request(
                    "GET", "/batch", query={"fids": ask})
                hst, _, hraw = req(v2, "GET", "/batch?fids=" + ask)
                assert fst == hst == 200, (fst, hst)
                assert fraw == hraw, "frame/HTTP batch bytes differ"
            finally:
                await ch.close()

        asyncio.run(frame_phase())
        st, _, out = req(v2, "GET", "/status")
        frames = json.loads(out).get("frames", {})
        hop_requests = sum(chs["requests"]
                           for per_w in frames.values()
                           for chs in per_w.values())
        assert hop_requests > 0, \
            f"sibling frame channels never used: {frames}"
        print(f"  frame hop: {len(fleet_fids)} single reads + 1 "
              f"cross-partition batch byte-equal over frames vs HTTP "
              f"({hop_requests} sibling frame requests)")
        print("wire smoke: OK")
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        time.sleep(0.5)


if __name__ == "__main__":
    sys.exit(main())
